"""mx.np — NumPy-compatible frontend over the TPU NDArray.

Reference analog: python/mxnet/numpy/multiarray.py:264 (``mx.np.ndarray``)
backed by the ``_npi.*`` C++ ops (reference src/operator/numpy/, 42k LoC).
In the TPU rebuild every _npi kernel collapses into the matching ``jnp``
call routed through the imperative invoke funnel (ops/registry.invoke_raw),
so each op is an XLA computation, autograd-tape-recordable, and jit-fusable.

Semantics follow NumPy with MXNet's deviations:
- default dtype float32 for creation ops (reference numpy/multiarray.py
  ``_np.float32`` default),
- arrays live on the current Context (mx.tpu()/mx.cpu()),
- ``out=`` rebinds the output handle (functional update under XLA).
"""
from __future__ import annotations

import builtins
import functools
from typing import Optional, Sequence, Tuple, Union

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import jx_dtype, dtype_name, MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, _put
from ..ops.registry import invoke_raw, set_np_ndarray_cls

__all__ = ["ndarray", "array", "asarray", "from_nd"]

_DEFAULT_DTYPE = jnp.float32
# x64 is disabled on TPU (perf); numpy's int64 defaults map to int32
_DEFAULT_INT = jnp.int32


def _adt(dtype):
    """Resolve a creation-op dtype: None → float32 (MXNet mx.np default)."""
    return _DEFAULT_DTYPE if dtype is None else jx_dtype(dtype)


class ndarray(NDArray):
    """mx.np.ndarray — NumPy drop-in array type.

    Subclasses the core NDArray (same XLA buffer + tape slots); the invoke
    funnel propagates this class to outputs, so inherited methods and all
    module functions return ``mx.np.ndarray``.
    """
    __slots__ = ()

    # ---- numpy-flavoured dunders (binary ops broadcast like numpy) ----
    def __add__(self, o):
        return add(self, o)

    def __radd__(self, o):
        return add(o, self)

    def __sub__(self, o):
        return subtract(self, o)

    def __rsub__(self, o):
        return subtract(o, self)

    def __mul__(self, o):
        return multiply(self, o)

    def __rmul__(self, o):
        return multiply(o, self)

    def __truediv__(self, o):
        return true_divide(self, o)

    def __rtruediv__(self, o):
        return true_divide(o, self)

    def __floordiv__(self, o):
        return floor_divide(self, o)

    def __rfloordiv__(self, o):
        return floor_divide(o, self)

    def __mod__(self, o):
        return mod(self, o)

    def __rmod__(self, o):
        return mod(o, self)

    def __pow__(self, o):
        return power(self, o)

    def __rpow__(self, o):
        return power(o, self)

    def __matmul__(self, o):
        return matmul(self, o)

    def __rmatmul__(self, o):
        return matmul(o, self)

    def __neg__(self):
        return negative(self)

    def __pos__(self):
        return self

    def __abs__(self):
        return absolute(self)

    def __invert__(self):
        return invert(self)

    def __and__(self, o):
        return bitwise_and(self, o)

    def __or__(self, o):
        return bitwise_or(self, o)

    def __xor__(self, o):
        return bitwise_xor(self, o)

    def __lshift__(self, o):
        return left_shift(self, o)

    def __rshift__(self, o):
        return right_shift(self, o)

    def __eq__(self, o):
        return equal(self, o)

    def __ne__(self, o):
        return not_equal(self, o)

    def __lt__(self, o):
        return less(self, o)

    def __le__(self, o):
        return less_equal(self, o)

    def __gt__(self, o):
        return greater(self, o)

    def __ge__(self, o):
        return greater_equal(self, o)

    __hash__ = None  # like numpy arrays

    # ---- NumPy interop protocols (reference multiarray.py:310,:367) ----
    # With these, official-NumPy calls dispatch on mx arrays:
    # ``onp.mean(mx_arr)`` runs mx.np.mean (on device); unimplemented
    # functions fall back to host numpy with a warning + recording guard.
    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        out = kwargs.pop("out", None)
        if out is not None:
            if not isinstance(out, tuple) or len(out) != 1:
                return NotImplemented
            out = out[0]
        if method != "__call__" or isinstance(out, onp.ndarray):
            # ufunc methods (reduce/accumulate/outer/...) and writes
            # into a host-numpy out= buffer keep host semantics: run the
            # real ufunc on host values (the pre-protocol behavior via
            # __array__ coercion; reference casting table keeps `a += b`
            # with onp `a` an onp result, multiarray.py:316)
            from . import fallback as _fb
            from .. import _tape
            if _tape.is_recording():
                raise MXNetError(
                    f"np.{ufunc.__name__}.{method} falls back to host "
                    "numpy (no gradient); it cannot run inside "
                    "autograd.record().")
            host_in = _fb._to_onp(inputs)
            bound = getattr(ufunc, method) if method != "__call__" \
                else ufunc
            if out is not None:
                return bound(*host_in, out=out, **_fb._to_onp(kwargs))
            return _fb._to_mx(bound(*host_in, **_fb._to_onp(kwargs)))
        if out is not None:
            kwargs["out"] = out
        return _dispatch_to_mx(ufunc.__name__, ufunc, inputs, kwargs)

    def __array_function__(self, func, types, args, kwargs):
        if not builtins.all(
                issubclass(t, ndarray) or t is onp.ndarray
                for t in types):
            return NotImplemented
        return _dispatch_to_mx(func.__name__, func, args, kwargs)

    def __repr__(self):
        if self._data is None:
            return "array(<uninitialized>)"
        try:
            body = repr(onp.asarray(self._data))
        except Exception:
            return f"array(<traced {self.shape} {dtype_name(self._data.dtype)}>)"
        body = body.replace("Array(", "array(").replace(
            "\n      ", "\n     ")
        if not body.startswith("array"):
            body = f"array({body})"
        ctx = self.context
        if str(ctx) != "cpu(0)":
            body = body[:-1] + f", ctx={ctx})"
        return body

    def __getitem__(self, key):
        res = super().__getitem__(key)
        return res

    # ---- numpy-style methods ----
    def reshape(self, *shape, order="C"):  # noqa: D102 — numpy semantics
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if len(shape) == 0:
            shape = ()
        return invoke_raw("np_reshape",
                          lambda x, _s=tuple(shape): jnp.reshape(x, _s),
                          [self])

    def flatten(self, order="C"):
        return self.reshape(-1)

    def ravel(self, order="C"):
        return self.reshape(-1)

    def tolist(self):
        return self.asnumpy().tolist()

    def copy(self):
        out = ndarray.__new__(ndarray)
        out._init_empty()
        out._data = self._data
        out._ctx = self._ctx
        return out

    def detach(self):
        out = ndarray.__new__(ndarray)
        out._init_empty()
        out._data = self._data
        out._ctx = self._ctx
        return out

    def as_np_ndarray(self):
        return self

    def as_nd_ndarray(self):
        out = NDArray.__new__(NDArray)
        out._init_empty()
        out._data = self._data
        out._ctx = self._ctx
        out._grad = self._grad
        out._tape_entry = self._tape_entry
        return out

    def std(self, axis=None, ddof=0, keepdims=False):
        return std(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return var(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def cumsum(self, axis=None, dtype=None):
        return cumsum(self, axis=axis, dtype=dtype)

    def dot(self, b):
        return dot(self, b)

    def nonzero(self):
        return nonzero(self)

    def round(self, decimals=0):
        return around(self, decimals)

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def argsort(self, axis=-1):
        return argsort(self, axis=axis)

    def sort(self, axis=-1):
        # numpy's method sorts in place; XLA buffers are immutable so rebind
        self._data = jnp.sort(self._data, axis=axis)
        return None

    def take(self, indices, axis=None, mode="raise"):
        return take(self, indices, axis=axis)

    def squeeze(self, axis=None):
        return invoke_raw("np_squeeze", lambda x: jnp.squeeze(x, axis), [self])

    def astype(self, dtype, copy=True):
        dt = jx_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return invoke_raw("np_cast", lambda x, _d=dt: x.astype(_d), [self])

    @property
    def T(self):
        return transpose(self)

    def attach_grad(self, grad_req="write", stype=None):
        super().attach_grad(grad_req, stype)
        self._grad = self._grad.as_np_ndarray()


set_np_ndarray_cls(ndarray)


# ------------------------------------------------------------------
# helpers
# ------------------------------------------------------------------
def _rejected_kwargs(fn, kwargs):
    """Kwargs ``fn`` STRUCTURALLY cannot accept, via inspect.signature —
    not exception-message sniffing, so a genuine TypeError raised inside
    an mx op (bad dtype/shape arg) is never mistaken for an unsupported
    ufunc option. Un-introspectable callables and **kwargs-takers accept
    everything by construction."""
    import inspect
    if not kwargs:
        return ()
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return ()
    params = sig.parameters.values()
    if builtins.any(p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params):
        return ()
    accepted = {p.name for p in params
                if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                              inspect.Parameter.KEYWORD_ONLY)}
    return tuple(k for k in kwargs if k not in accepted)


def _dispatch_to_mx(name, onp_func, args, kwargs):
    """Route an official-NumPy function/ufunc call whose arguments
    include mx arrays: prefer the mx.np implementation (device compute,
    tape-recordable); otherwise fall back to host numpy via the
    fallback wrapper (warn once, refuse under autograd recording)."""
    from . import fallback as _fb
    import mxnet_tpu.numpy as mx_np
    mx_fn = getattr(mx_np, name, None)
    if callable(mx_fn) and not getattr(mx_fn, "_is_np_fallback", False):
        if _rejected_kwargs(mx_fn, kwargs):
            # a legal ufunc option (np_ufunc_legal_option: where=, …) the
            # mx implementation doesn't declare — keep protocol semantics
            # by falling back to host (refused under autograd recording
            # by the fallback wrapper). Detected BEFORE the call, so
            # TypeErrors raised inside the mx op propagate unchanged.
            return _fb.make_fallback(name, onp_func)(*args, **kwargs)
        return mx_fn(*_fb._to_mx(args), **_fb._to_mx(kwargs))
    if getattr(mx_fn, "_is_np_fallback", False):
        return mx_fn(*args, **kwargs)  # installed wrapper converts itself
    return _fb.make_fallback(name, onp_func)(*args, **kwargs)


def _seq_has_nd(x):
    return isinstance(x, (list, tuple)) and builtins.any(
        isinstance(e, NDArray) for e in x)


def _invoke(name, fn, arrays, n_outputs=1):
    """Route through the imperative funnel; force np ndarray outputs."""
    return invoke_raw(name, fn, list(arrays), n_outputs=n_outputs,
                      out_cls=ndarray)


def _maybe_out(res, out):
    if out is not None:
        if isinstance(res, tuple):
            for o, r in zip(out, res):
                o._data = r._data
                o._tape_entry = r._tape_entry
            return out
        out._data = res._data
        out._tape_entry = res._tape_entry
        return out
    return res


def _unary(name, jfn):
    def f(x, out=None, **kwargs):
        if isinstance(x, NDArray):
            res = _invoke(name, functools.partial(jfn, **kwargs) if kwargs
                          else jfn, [x])
        else:
            res = ndarray(jfn(jnp.asarray(x), **kwargs))
        return _maybe_out(res, out)
    f.__name__ = name
    f.__doc__ = f"mx.np.{name} — NumPy-compatible; lowers to jnp.{name} (XLA)."
    return f


def _binary(name, jfn):
    def f(x1, x2, out=None):
        a1, a2 = isinstance(x1, NDArray), isinstance(x2, NDArray)
        if a1 and a2:
            res = _invoke(name, jfn, [x1, x2])
        elif a1:
            # scalar is closure-captured so jnp weak-type promotion applies
            res = _invoke(name, lambda a, _b=x2: jfn(a, _b), [x1])
        elif a2:
            res = _invoke(name, lambda b, _a=x1: jfn(_a, b), [x2])
        else:
            res = ndarray(jfn(jnp.asarray(x1), jnp.asarray(x2)))
        return _maybe_out(res, out)
    f.__name__ = name
    f.__doc__ = f"mx.np.{name} — NumPy-compatible; lowers to jnp.{name} (XLA)."
    return f


def _reduction(name, jfn, has_dtype=True):
    def f(a, axis=None, dtype=None, out=None, keepdims=False, **kw):
        ax = tuple(axis) if isinstance(axis, list) else axis
        kwargs = dict(axis=ax, keepdims=keepdims, **kw)
        if has_dtype and dtype is not None:
            kwargs["dtype"] = jx_dtype(dtype)
        res = _invoke(name, lambda x: jfn(x, **kwargs),
                      [a if isinstance(a, NDArray) else ndarray(a)])
        return _maybe_out(res, out)
    f.__name__ = name
    return f


# ------------------------------------------------------------------
# creation
# ------------------------------------------------------------------
def array(object, dtype=None, ctx=None):
    """Create an mx.np.ndarray (reference numpy/multiarray.py ``array``)."""
    if isinstance(object, NDArray):
        data = object._data
        if dtype is not None:
            data = data.astype(jx_dtype(dtype))
        out = ndarray.__new__(ndarray)
        out._init_empty()
        out._data = _put(data, ctx) if ctx is not None else data
        out._ctx = ctx
        return out
    keep_dtype = isinstance(object, (onp.ndarray, onp.generic))
    a = onp.asarray(object, dtype=None if dtype is None else jx_dtype(dtype))
    if dtype is None:
        if not keep_dtype and a.dtype != onp.bool_:
            # reference numpy/multiarray.py array(): python lists/scalars
            # default to float32 regardless of element type
            a = a.astype(onp.float32)
        elif a.dtype == onp.float64:
            a = a.astype(onp.float32)
        elif a.dtype == onp.int64:
            a = a.astype(onp.int32)  # x64 disabled: int64 maps to int32
    return ndarray(_put(a, ctx), ctx=ctx)


def asarray(obj, dtype=None):
    if isinstance(obj, ndarray) and dtype is None:
        return obj
    return array(obj, dtype=dtype)


def from_nd(x: NDArray) -> ndarray:
    return x.as_np_ndarray()


def _creation(name, jfn):
    def f(shape, dtype=None, order="C", ctx=None):
        if isinstance(shape, (int, onp.integer)):
            shape = (int(shape),)
        res = ndarray(jfn(tuple(shape), dtype=_adt(dtype)), ctx=ctx)
        return res
    f.__name__ = name
    return f


zeros = _creation("zeros", jnp.zeros)
ones = _creation("ones", jnp.ones)
empty = _creation("empty", jnp.zeros)


def full(shape, fill_value, dtype=None, order="C", ctx=None, out=None):
    if isinstance(shape, (int, onp.integer)):
        shape = (int(shape),)
    if dtype is None:
        if isinstance(fill_value, (bool, onp.bool_)):
            dt = jnp.bool_
        elif isinstance(fill_value, (int, onp.integer)):
            dt = _DEFAULT_INT
        else:
            dt = _DEFAULT_DTYPE
    else:
        dt = jx_dtype(dtype)
    if isinstance(fill_value, NDArray):
        fill_value = fill_value._data
    return _maybe_out(ndarray(jnp.full(tuple(shape), fill_value, dtype=dt),
                              ctx=ctx), out)


def zeros_like(a, dtype=None, order="C", ctx=None):
    return _invoke("zeros_like",
                   lambda x: jnp.zeros_like(x, dtype=None if dtype is None
                                            else jx_dtype(dtype)),
                   [a if isinstance(a, NDArray) else ndarray(a)])


def ones_like(a, dtype=None, order="C", ctx=None):
    return _invoke("ones_like",
                   lambda x: jnp.ones_like(x, dtype=None if dtype is None
                                           else jx_dtype(dtype)),
                   [a if isinstance(a, NDArray) else ndarray(a)])


def full_like(a, fill_value, dtype=None, order="C", ctx=None):
    return _invoke("full_like",
                   lambda x: jnp.full_like(x, fill_value,
                                           dtype=None if dtype is None
                                           else jx_dtype(dtype)),
                   [a if isinstance(a, NDArray) else ndarray(a)])


empty_like = zeros_like


def eye(N, M=None, k=0, dtype=None, ctx=None):
    return ndarray(jnp.eye(N, M, k=k, dtype=_adt(dtype)), ctx=ctx)


def identity(n, dtype=None, ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    if dtype is None:
        if builtins.all(isinstance(v, (int, onp.integer)) or v is None
                        for v in (start, stop, step)):
            dt = _DEFAULT_INT
        else:
            dt = _DEFAULT_DTYPE
    else:
        dt = jx_dtype(dtype)
    return ndarray(jnp.arange(start, stop, step, dtype=dt), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    res = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=_adt(dtype), axis=axis)
    if retstep:
        return ndarray(res[0], ctx=ctx), float(res[1])
    return ndarray(res, ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None):
    return ndarray(jnp.logspace(start, stop, num, endpoint=endpoint,
                                base=base, dtype=_adt(dtype), axis=axis),
                   ctx=ctx)


def meshgrid(*xi, indexing="xy", **kwargs):
    datas = [x._data if isinstance(x, NDArray) else jnp.asarray(x) for x in xi]
    return [ndarray(g) for g in jnp.meshgrid(*datas, indexing=indexing)]


def tril(m, k=0):
    return _invoke("tril", lambda x: jnp.tril(x, k), [m])


def triu(m, k=0):
    return _invoke("triu", lambda x: jnp.triu(x, k), [m])


def tri(N, M=None, k=0, dtype=None, ctx=None):
    return ndarray(jnp.tri(N, M, k, dtype=_adt(dtype)), ctx=ctx)


def indices(dimensions, dtype=None, ctx=None):
    return ndarray(jnp.indices(tuple(dimensions),
                               dtype=_DEFAULT_INT if dtype is None
                               else jx_dtype(dtype)), ctx=ctx)


def diag(v, k=0):
    return _invoke("diag", lambda x: jnp.diag(x, k), [v])


def diagflat(v, k=0):
    return _invoke("diagflat", lambda x: jnp.diagflat(x, k), [v])


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _invoke("diagonal",
                   lambda x: jnp.diagonal(x, offset, axis1, axis2), [a])


def atleast_1d(*arys):
    res = [_invoke("atleast_1d", jnp.atleast_1d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_2d(*arys):
    res = [_invoke("atleast_2d", jnp.atleast_2d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_3d(*arys):
    res = [_invoke("atleast_3d", jnp.atleast_3d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


def copy(a):
    return a.copy() if isinstance(a, ndarray) else array(a)


# ------------------------------------------------------------------
# ufuncs — unary
# ------------------------------------------------------------------
negative = _unary("negative", jnp.negative)
positive = _unary("positive", jnp.positive)
absolute = _unary("absolute", jnp.abs)
abs = absolute  # noqa: A001
fabs = _unary("fabs", jnp.fabs)
sign = _unary("sign", jnp.sign)
rint = _unary("rint", jnp.rint)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
trunc = _unary("trunc", jnp.trunc)
fix = _unary("fix", jnp.trunc)  # round toward zero (jnp.fix deprecated)
sqrt = _unary("sqrt", jnp.sqrt)
cbrt = _unary("cbrt", jnp.cbrt)
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", jnp.reciprocal)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
exp2 = _unary("exp2", jnp.exp2)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
arcsin = _unary("arcsin", jnp.arcsin)
arccos = _unary("arccos", jnp.arccos)
arctan = _unary("arctan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
arcsinh = _unary("arcsinh", jnp.arcsinh)
arccosh = _unary("arccosh", jnp.arccosh)
arctanh = _unary("arctanh", jnp.arctanh)
degrees = _unary("degrees", jnp.degrees)
radians = _unary("radians", jnp.radians)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
invert = _unary("invert", jnp.invert)
bitwise_not = invert
logical_not = _unary("logical_not", jnp.logical_not)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
isposinf = _unary("isposinf", jnp.isposinf)
isneginf = _unary("isneginf", jnp.isneginf)
conj = _unary("conj", jnp.conj)
conjugate = conj
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
angle = _unary("angle", jnp.angle)
sinc = _unary("sinc", jnp.sinc)
nan_to_num = _unary("nan_to_num", jnp.nan_to_num)
i0 = _unary("i0", jnp.i0)

# ------------------------------------------------------------------
# ufuncs — binary
# ------------------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
true_divide = _binary("true_divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
fmod = _binary("fmod", jnp.fmod)
power = _binary("power", jnp.power)
float_power = _binary("float_power", jnp.float_power)
arctan2 = _binary("arctan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
copysign = _binary("copysign", jnp.copysign)
ldexp = _binary("ldexp", jnp.ldexp)
nextafter = _binary("nextafter", jnp.nextafter)
logaddexp = _binary("logaddexp", jnp.logaddexp)
logaddexp2 = _binary("logaddexp2", jnp.logaddexp2)
bitwise_and = _binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary("bitwise_xor", jnp.bitwise_xor)
left_shift = _binary("left_shift", jnp.left_shift)
right_shift = _binary("right_shift", jnp.right_shift)
logical_and = _binary("logical_and", jnp.logical_and)
logical_or = _binary("logical_or", jnp.logical_or)
logical_xor = _binary("logical_xor", jnp.logical_xor)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
heaviside = _binary("heaviside", jnp.heaviside)
equal = _binary("equal", jnp.equal)
not_equal = _binary("not_equal", jnp.not_equal)
greater = _binary("greater", jnp.greater)
greater_equal = _binary("greater_equal", jnp.greater_equal)
less = _binary("less", jnp.less)
less_equal = _binary("less_equal", jnp.less_equal)

# ------------------------------------------------------------------
# reductions
# ------------------------------------------------------------------
sum = _reduction("sum", jnp.sum)  # noqa: A001
prod = _reduction("prod", jnp.prod)
mean = _reduction("mean", jnp.mean)
nansum = _reduction("nansum", jnp.nansum)
nanprod = _reduction("nanprod", jnp.nanprod)
nanmean = _reduction("nanmean", jnp.nanmean)


def _reduction_nd(name, jfn):
    def f(a, axis=None, out=None, keepdims=False):
        ax = tuple(axis) if isinstance(axis, list) else axis
        res = _invoke(name, lambda x: jfn(x, axis=ax, keepdims=keepdims),
                      [a if isinstance(a, NDArray) else ndarray(a)])
        return _maybe_out(res, out)
    f.__name__ = name
    return f


amax = _reduction_nd("max", jnp.max)
amin = _reduction_nd("min", jnp.min)
max = amax  # noqa: A001
min = amin  # noqa: A001
nanmax = _reduction_nd("nanmax", jnp.nanmax)
nanmin = _reduction_nd("nanmin", jnp.nanmin)
all = _reduction_nd("all", jnp.all)  # noqa: A001
any = _reduction_nd("any", jnp.any)  # noqa: A001


def std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, list) else axis
    res = _invoke("std", lambda x: jnp.std(x, axis=ax, ddof=ddof,
                                           keepdims=keepdims),
                  [a if isinstance(a, NDArray) else ndarray(a)])
    return _maybe_out(res, out)


def var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, list) else axis
    res = _invoke("var", lambda x: jnp.var(x, axis=ax, ddof=ddof,
                                           keepdims=keepdims),
                  [a if isinstance(a, NDArray) else ndarray(a)])
    return _maybe_out(res, out)


def argmax(a, axis=None, out=None):
    res = _invoke("argmax", lambda x: jnp.argmax(x, axis=axis), [a])
    return _maybe_out(res, out)


def argmin(a, axis=None, out=None):
    res = _invoke("argmin", lambda x: jnp.argmin(x, axis=axis), [a])
    return _maybe_out(res, out)


def nanargmax(a, axis=None):
    return _invoke("nanargmax", lambda x: jnp.nanargmax(x, axis=axis), [a])


def nanargmin(a, axis=None):
    return _invoke("nanargmin", lambda x: jnp.nanargmin(x, axis=axis), [a])


def median(a, axis=None, out=None, keepdims=False):
    res = _invoke("median",
                  lambda x: jnp.median(x, axis=axis, keepdims=keepdims), [a])
    return _maybe_out(res, out)


def quantile(a, q, axis=None, out=None, interpolation="linear",
             keepdims=False):
    qv = q._data if isinstance(q, NDArray) else q
    res = _invoke("quantile",
                  lambda x: jnp.quantile(x, jnp.asarray(qv), axis=axis,
                                         method=interpolation,
                                         keepdims=keepdims), [a])
    return _maybe_out(res, out)


def percentile(a, q, axis=None, out=None, interpolation="linear",
               keepdims=False):
    qv = q._data if isinstance(q, NDArray) else q
    res = _invoke("percentile",
                  lambda x: jnp.percentile(x, jnp.asarray(qv), axis=axis,
                                           method=interpolation,
                                           keepdims=keepdims), [a])
    return _maybe_out(res, out)


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        res = mean(a, axis=axis)
        if returned:
            cnt = a.size if axis is None else a.shape[axis]
            return res, full(res.shape, float(cnt))
        return res
    arrs = [a, weights] if isinstance(weights, NDArray) else [a]
    if isinstance(weights, NDArray):
        res = _invoke("average",
                      lambda x, w: jnp.average(x, axis=axis, weights=w), arrs)
    else:
        res = _invoke("average",
                      lambda x: jnp.average(x, axis=axis,
                                            weights=jnp.asarray(weights)),
                      arrs)
    if returned:
        if isinstance(weights, NDArray):
            sw = sum(weights, axis=axis)
        else:
            sw = ndarray(jnp.sum(jnp.asarray(weights), axis=axis))
        if sw.shape != res.shape:
            sw = broadcast_to(sw, res.shape)
        return res, sw
    return res


def cumsum(a, axis=None, dtype=None, out=None):
    res = _invoke("cumsum",
                  lambda x: jnp.cumsum(x, axis=axis,
                                       dtype=None if dtype is None
                                       else jx_dtype(dtype)), [a])
    return _maybe_out(res, out)


def cumprod(a, axis=None, dtype=None):
    return _invoke("cumprod",
                   lambda x: jnp.cumprod(x, axis=axis,
                                         dtype=None if dtype is None
                                         else jx_dtype(dtype)), [a])


def count_nonzero(a, axis=None):
    return _invoke("count_nonzero",
                   lambda x: jnp.count_nonzero(x, axis=axis), [a])


def ptp(a, axis=None, keepdims=False):
    return _invoke("ptp", lambda x: jnp.ptp(x, axis=axis, keepdims=keepdims),
                   [a])


# ------------------------------------------------------------------
# manipulation
# ------------------------------------------------------------------
def reshape(a, newshape, order="C"):
    if isinstance(newshape, (int, onp.integer)):
        newshape = (int(newshape),)
    return _invoke("np_reshape",
                   lambda x, _s=tuple(newshape): jnp.reshape(x, _s), [a])


def ravel(a, order="C"):
    return reshape(a, -1)


def transpose(a, axes=None):
    return _invoke("np_transpose", lambda x: jnp.transpose(x, axes), [a])


def swapaxes(a, axis1, axis2):
    return _invoke("np_swapaxes", lambda x: jnp.swapaxes(x, axis1, axis2),
                   [a])


def moveaxis(a, source, destination):
    return _invoke("np_moveaxis",
                   lambda x: jnp.moveaxis(x, source, destination), [a])


def rollaxis(a, axis, start=0):
    return _invoke("np_rollaxis", lambda x: jnp.rollaxis(x, axis, start), [a])


def expand_dims(a, axis):
    return _invoke("np_expand_dims", lambda x: jnp.expand_dims(x, axis), [a])


def squeeze(a, axis=None):
    return _invoke("np_squeeze", lambda x: jnp.squeeze(x, axis), [a])


def broadcast_to(array_, shape):
    a = array_ if isinstance(array_, NDArray) else array(array_)
    return _invoke("np_broadcast_to",
                   lambda x, _s=tuple(shape): jnp.broadcast_to(x, _s), [a])


def broadcast_arrays(*args):
    arrs = [a if isinstance(a, NDArray) else array(a) for a in args]
    shp = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [broadcast_to(a, shp) for a in arrs]


def _join(name, jfn):
    def f(seq, axis=0, out=None):
        arrs = [a if isinstance(a, NDArray) else array(a) for a in seq]
        if name in ("vstack", "hstack", "dstack", "column_stack"):
            res = _invoke(name, lambda *xs: jfn(xs), arrs)
        else:
            res = _invoke(name, lambda *xs: jfn(xs, axis=axis), arrs)
        return _maybe_out(res, out)
    f.__name__ = name
    return f


concatenate = _join("concatenate", jnp.concatenate)
stack = _join("stack", jnp.stack)
vstack = _join("vstack", jnp.vstack)
hstack = _join("hstack", jnp.hstack)
dstack = _join("dstack", jnp.dstack)
column_stack = _join("column_stack", jnp.column_stack)


def concat(seq, axis=0, out=None):
    return concatenate(seq, axis=axis, out=out)


def append(arr, values, axis=None):
    a = arr if isinstance(arr, NDArray) else array(arr)
    v = values if isinstance(values, NDArray) else array(values)
    return _invoke("append", lambda x, y: jnp.append(x, y, axis=axis), [a, v])


def _split_impl(name, a, indices_or_sections, axis):
    data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    if isinstance(indices_or_sections, NDArray):
        indices_or_sections = tuple(indices_or_sections.asnumpy().tolist())
    if name == "split":
        def fn(x):
            return tuple(jnp.split(x, indices_or_sections, axis=axis))
    else:
        def fn(x):
            return tuple(getattr(jnp, name)(x, indices_or_sections))
    n = len(fn(jnp.zeros(data.shape, data.dtype)))  # static split count
    res = _invoke(name, fn, [a if isinstance(a, NDArray) else ndarray(a)],
                  n_outputs=n)
    return builtins.list(res) if isinstance(res, tuple) else [res]


def split(ary, indices_or_sections, axis=0):
    return _split_impl("split", ary, indices_or_sections, axis)


def array_split(ary, indices_or_sections, axis=0):
    data = ary._data if isinstance(ary, NDArray) else jnp.asarray(ary)
    n = len(jnp.array_split(data, indices_or_sections, axis=axis))
    res = _invoke("array_split",
                  lambda x: tuple(jnp.array_split(x, indices_or_sections,
                                                  axis=axis)),
                  [ary if isinstance(ary, NDArray) else ndarray(ary)],
                  n_outputs=n)
    return builtins.list(res) if isinstance(res, tuple) else [res]


def hsplit(ary, indices_or_sections):
    return _split_impl("hsplit", ary, indices_or_sections, None)


def vsplit(ary, indices_or_sections):
    return _split_impl("vsplit", ary, indices_or_sections, None)


def dsplit(ary, indices_or_sections):
    return _split_impl("dsplit", ary, indices_or_sections, None)


def tile(A, reps):
    return _invoke("np_tile", lambda x: jnp.tile(x, reps),
                   [A if isinstance(A, NDArray) else ndarray(A)])


def repeat(a, repeats, axis=None):
    return _invoke("np_repeat", lambda x: jnp.repeat(x, repeats, axis), [a])


def flip(m, axis=None):
    return _invoke("np_flip", lambda x: jnp.flip(x, axis), [m])


def fliplr(m):
    return _invoke("fliplr", jnp.fliplr, [m])


def flipud(m):
    return _invoke("flipud", jnp.flipud, [m])


def roll(a, shift, axis=None):
    return _invoke("roll", lambda x: jnp.roll(x, shift, axis), [a])


def rot90(m, k=1, axes=(0, 1)):
    return _invoke("rot90", lambda x: jnp.rot90(x, k, axes), [m])


def pad(array_, pad_width, mode="constant", **kwargs):
    a = array_ if isinstance(array_, NDArray) else array(array_)
    return _invoke("np_pad",
                   lambda x: jnp.pad(x, pad_width, mode=mode, **kwargs), [a])


def insert(arr, obj, values, axis=None):
    a = arr if isinstance(arr, NDArray) else array(arr)
    v = values._data if isinstance(values, NDArray) else values
    if isinstance(obj, NDArray):
        obj = onp.asarray(obj.asnumpy(), dtype=onp.int32)
    return _invoke("insert", lambda x: jnp.insert(x, obj, v, axis=axis), [a])


def delete(arr, obj, axis=None):
    a = arr if isinstance(arr, NDArray) else array(arr)
    if isinstance(obj, NDArray):
        obj = onp.asarray(obj.asnumpy(), dtype=onp.int32)
    elif isinstance(obj, (list, tuple)):  # numpy accepts index lists
        obj = onp.asarray(obj, dtype=onp.int32)
    return _invoke("delete", lambda x: jnp.delete(x, obj, axis=axis), [a])


def unique(ar, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    data = ar._data if isinstance(ar, NDArray) else jnp.asarray(ar)
    res = jnp.unique(data, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(ndarray(r) for r in res)
    return ndarray(res)


def sort(a, axis=-1, kind=None, order=None):
    return _invoke("np_sort", lambda x: jnp.sort(x, axis=axis), [a])


def argsort(a, axis=-1, kind=None, order=None):
    return _invoke("np_argsort", lambda x: jnp.argsort(x, axis=axis), [a])


def searchsorted(a, v, side="left", sorter=None):
    arrs = [a, v] if isinstance(v, NDArray) else [a]
    if isinstance(v, NDArray):
        return _invoke("searchsorted",
                       lambda x, y: jnp.searchsorted(x, y, side=side), arrs)
    return _invoke("searchsorted",
                   lambda x: jnp.searchsorted(x, jnp.asarray(v), side=side),
                   arrs)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    c = condition if isinstance(condition, NDArray) else array(condition)
    arrs = [c]
    xin = yin = None
    if isinstance(x, NDArray):
        xin = len(arrs)
        arrs.append(x)
    if isinstance(y, NDArray):
        yin = len(arrs)
        arrs.append(y)

    def fn(*datas):
        xv = datas[xin] if xin is not None else x
        yv = datas[yin] if yin is not None else y
        return jnp.where(datas[0], xv, yv)
    return _invoke("where", fn, arrs)


def clip(a, a_min=None, a_max=None, out=None):
    res = _invoke("np_clip", lambda x: jnp.clip(x, a_min, a_max),
                  [a if isinstance(a, NDArray) else ndarray(a)])
    return _maybe_out(res, out)


def around(a, decimals=0, out=None):
    res = _invoke("around", lambda x: jnp.around(x, decimals),
                  [a if isinstance(a, NDArray) else ndarray(a)])
    return _maybe_out(res, out)


round = around  # noqa: A001
round_ = around


def take(a, indices, axis=None, mode="raise", out=None):
    arr = a if isinstance(a, NDArray) else array(a)
    jmode = None if mode == "raise" else mode
    if isinstance(indices, NDArray):
        res = _invoke("np_take",
                      lambda x, i: jnp.take(x, i.astype(_DEFAULT_INT)
                                            if i.dtype not in (jnp.int32, _DEFAULT_INT)
                                            else i, axis=axis, mode=jmode),
                      [arr, indices])
    else:
        idx = jnp.asarray(onp.asarray(indices, dtype=onp.int64))
        res = _invoke("np_take",
                      lambda x: jnp.take(x, idx, axis=axis, mode=jmode),
                      [arr])
    return _maybe_out(res, out)


def take_along_axis(arr, indices, axis):
    return _invoke("take_along_axis",
                   lambda x, i: jnp.take_along_axis(
                       x, i.astype(_DEFAULT_INT), axis=axis),
                   [arr, indices])


def nonzero(a):
    data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    return tuple(ndarray(r) for r in onp.nonzero(onp.asarray(data)))


def flatnonzero(a):
    data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    return ndarray(onp.flatnonzero(onp.asarray(data)))


def argwhere(a):
    data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    return ndarray(onp.argwhere(onp.asarray(data)))


def diff(a, n=1, axis=-1):
    return _invoke("diff", lambda x: jnp.diff(x, n=n, axis=axis), [a])


def ediff1d(ary, to_end=None, to_begin=None):
    return _invoke("ediff1d",
                   lambda x: jnp.ediff1d(x, to_end=to_end,
                                         to_begin=to_begin), [ary])


def gradient(f, *varargs, axis=None, edge_order=1):
    data = f._data if isinstance(f, NDArray) else jnp.asarray(f)
    res = jnp.gradient(data, *varargs, axis=axis)
    if isinstance(res, (builtins.list, tuple)):
        return [ndarray(d) for d in res]
    return ndarray(res)


def trapz(y, x=None, dx=1.0, axis=-1):
    if x is not None and isinstance(x, NDArray):
        return _invoke("trapz",
                       lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                       [y, x])
    xv = None if x is None else jnp.asarray(x)
    return _invoke("trapz",
                   lambda yy: jnp.trapezoid(yy, xv, dx=dx, axis=axis), [y])


def interp(x, xp, fp, left=None, right=None, period=None):
    datas = [v._data if isinstance(v, NDArray) else jnp.asarray(v)
             for v in (x, xp, fp)]
    return ndarray(jnp.interp(*datas, left=left, right=right, period=period))


def cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    return _invoke("cross",
                   lambda x, y: jnp.cross(x, y, axisa, axisb, axisc,
                                          axis=axis),
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def convolve(a, v, mode="full"):
    return _invoke("convolve", lambda x, y: jnp.convolve(x, y, mode=mode),
                   [a if isinstance(a, NDArray) else array(a),
                    v if isinstance(v, NDArray) else array(v)])


def correlate(a, v, mode="valid"):
    return _invoke("correlate", lambda x, y: jnp.correlate(x, y, mode=mode),
                   [a if isinstance(a, NDArray) else array(a),
                    v if isinstance(v, NDArray) else array(v)])


def resize(a, new_shape):
    return _invoke("np_resize",
                   lambda x: jnp.resize(x, new_shape),
                   [a if isinstance(a, NDArray) else ndarray(a)])


# ------------------------------------------------------------------
# linear algebra (top-level)
# ------------------------------------------------------------------
def dot(a, b, out=None):
    res = _invoke("np_dot", jnp.dot,
                  [a if isinstance(a, NDArray) else array(a),
                   b if isinstance(b, NDArray) else array(b)])
    return _maybe_out(res, out)


def matmul(a, b, out=None):
    res = _invoke("np_matmul", jnp.matmul,
                  [a if isinstance(a, NDArray) else array(a),
                   b if isinstance(b, NDArray) else array(b)])
    return _maybe_out(res, out)


def inner(a, b):
    return _invoke("inner", jnp.inner,
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def outer(a, b):
    return _invoke("outer", jnp.outer,
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def vdot(a, b):
    return _invoke("vdot", jnp.vdot,
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def tensordot(a, b, axes=2):
    return _invoke("tensordot", lambda x, y: jnp.tensordot(x, y, axes=axes),
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def einsum(subscripts, *operands, out=None, optimize=False):
    arrs = [o if isinstance(o, NDArray) else array(o) for o in operands]
    res = _invoke("einsum",
                  lambda *datas: jnp.einsum(subscripts, *datas), arrs)
    return _maybe_out(res, out)


def kron(a, b):
    return _invoke("kron", jnp.kron,
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def trace(a, offset=0, axis1=0, axis2=1):
    return _invoke("trace", lambda x: jnp.trace(x, offset, axis1, axis2), [a])


def matrix_power(a, n):
    from . import linalg
    return linalg.matrix_power(a, n)


def vander(x, N=None, increasing=False):
    return _invoke("vander",
                   lambda v: jnp.vander(v, N, increasing=increasing),
                   [x if isinstance(x, NDArray) else array(x)])


# ------------------------------------------------------------------
# statistics / histograms
# ------------------------------------------------------------------
def histogram(a, bins=10, range=None, weights=None, density=None):
    data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    b = bins._data if isinstance(bins, NDArray) else bins
    hist, edges = jnp.histogram(data, bins=b, range=range,
                                weights=None if weights is None
                                else jnp.asarray(weights), density=density)
    return ndarray(hist), ndarray(edges)


def bincount(x, weights=None, minlength=0):
    data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    return ndarray(jnp.bincount(
        data.astype(jnp.int32),
        weights=None if weights is None else jnp.asarray(
            weights._data if isinstance(weights, NDArray) else weights),
        minlength=minlength))


def digitize(x, bins, right=False):
    data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    b = bins._data if isinstance(bins, NDArray) else jnp.asarray(bins)
    return ndarray(jnp.digitize(data, b, right=right))


def cov(m, y=None, rowvar=True, bias=False, ddof=None):
    data = m._data if isinstance(m, NDArray) else jnp.asarray(m)
    yv = None if y is None else (y._data if isinstance(y, NDArray)
                                 else jnp.asarray(y))
    return ndarray(jnp.cov(data, yv, rowvar=rowvar, bias=bias, ddof=ddof))


def corrcoef(x, y=None, rowvar=True):
    data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    yv = None if y is None else (y._data if isinstance(y, NDArray)
                                 else jnp.asarray(y))
    return ndarray(jnp.corrcoef(data, yv, rowvar=rowvar))


# ------------------------------------------------------------------
# logic
# ------------------------------------------------------------------
def array_equal(a1, a2, equal_nan=False):
    d1 = a1._data if isinstance(a1, NDArray) else jnp.asarray(a1)
    d2 = a2._data if isinstance(a2, NDArray) else jnp.asarray(a2)
    return builtins.bool(jnp.array_equal(d1, d2, equal_nan=equal_nan))


def array_equiv(a1, a2):
    d1 = a1._data if isinstance(a1, NDArray) else jnp.asarray(a1)
    d2 = a2._data if isinstance(a2, NDArray) else jnp.asarray(a2)
    return builtins.bool(jnp.array_equiv(d1, d2))


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    d1 = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    d2 = b._data if isinstance(b, NDArray) else jnp.asarray(b)
    return builtins.bool(jnp.allclose(d1, d2, rtol, atol, equal_nan))


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _invoke("isclose",
                   lambda x, y: jnp.isclose(x, y, rtol, atol, equal_nan),
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)])


def isscalar(element):
    return onp.isscalar(element)


def shares_memory(a, b, max_work=None):
    da = a._data if isinstance(a, NDArray) else a
    db = b._data if isinstance(b, NDArray) else b
    return da is db


may_share_memory = shares_memory


def result_type(*arrays_and_dtypes):
    args = [a._data if isinstance(a, NDArray)
            else (jx_dtype(a) if isinstance(a, (str, type, onp.dtype))
                  else a)
            for a in arrays_and_dtypes]
    return onp.dtype(str(jnp.result_type(*args)))


def promote_types(t1, t2):
    return onp.dtype(str(jnp.promote_types(jx_dtype(t1), jx_dtype(t2))))


def can_cast(from_, to, casting="safe"):
    f = from_._data.dtype if isinstance(from_, NDArray) else jx_dtype(from_)
    return onp.can_cast(onp.dtype(str(f)), onp.dtype(str(jx_dtype(to))),
                        casting=casting)


def ndim(a):
    return a.ndim if isinstance(a, NDArray) else onp.ndim(a)


def shape(a):
    return a.shape if isinstance(a, NDArray) else onp.shape(a)


def size(a, axis=None):
    if isinstance(a, NDArray):
        return a.size if axis is None else a.shape[axis]
    return onp.size(a, axis)


def may_apply_along(a):  # pragma: no cover — placeholder
    raise NotImplementedError


# ---- window functions + remaining numpy API surface (reference
# src/operator/numpy/np_window_op.cc et al.) ----

def hanning(M, dtype=None, ctx=None):
    return ndarray(jnp.hanning(M).astype(_adt(dtype)),
                   ctx=ctx)


def hamming(M, dtype=None, ctx=None):
    return ndarray(jnp.hamming(M).astype(_adt(dtype)),
                   ctx=ctx)


def blackman(M, dtype=None, ctx=None):
    return ndarray(jnp.blackman(M).astype(_adt(dtype)),
                   ctx=ctx)


def kaiser(M, beta, dtype=None, ctx=None):
    return ndarray(jnp.kaiser(M, beta).astype(_adt(dtype)),
                   ctx=ctx)


def bartlett(M, dtype=None, ctx=None):
    return ndarray(jnp.bartlett(M).astype(_adt(dtype)), ctx=ctx)


def trim_zeros(filt, trim="fb"):
    """Trim leading/trailing zeros (reference _npi_trim_zeros). Host-side
    (output shape is data-dependent — same as the reference's CPU path)."""
    arr = onp.trim_zeros(onp.asarray(filt._data if hasattr(filt, "_data")
                                     else filt), trim)
    return ndarray(jnp.asarray(arr))


def apply_along_axis(func1d, axis, arr, *args, **kwargs):
    """NumPy-parity apply_along_axis: vmap the 1-D function over every
    other axis (compiled batching instead of the host loop)."""
    a = arr._data if hasattr(arr, "_data") else jnp.asarray(arr)
    axis = axis % a.ndim
    moved = jnp.moveaxis(a, axis, -1)
    lead_shape = moved.shape[:-1]
    flat = moved.reshape(-1, moved.shape[-1])

    def f1d(row):
        out = func1d(ndarray(row), *args, **kwargs)
        return out._data if hasattr(out, "_data") else jnp.asarray(out)

    out = jax.vmap(f1d)(flat)
    fo_shape = out.shape[1:]
    out = out.reshape(lead_shape + fo_shape)
    # NumPy inserts the func1d output dims AT `axis` (not at the end)
    nl, nf = len(lead_shape), len(fo_shape)
    out = jnp.moveaxis(out, tuple(range(nl, nl + nf)),
                       tuple(range(axis, axis + nf)))
    return ndarray(out)


def polyval(p, x):
    pd = p._data if hasattr(p, "_data") else jnp.asarray(p)
    xd = x._data if hasattr(x, "_data") else jnp.asarray(x)
    return ndarray(jnp.polyval(pd, xd))


def diag_indices_from(arr):
    a = arr._data if hasattr(arr, "_data") else jnp.asarray(arr)
    return tuple(ndarray(i) for i in jnp.diag_indices_from(a))


def tril_indices(n, k=0, m=None):
    return tuple(ndarray(i) for i in jnp.tril_indices(n, k, m))


def triu_indices(n, k=0, m=None, ctx=None):
    """Indices of the upper triangle of an (n, m) array (reference
    numpy/multiarray.py:5902)."""
    return tuple(ndarray(i) for i in jnp.triu_indices(n, k, m))


def triu_indices_from(arr, k=0):
    a = arr._data if hasattr(arr, "_data") else jnp.asarray(arr)
    return tuple(ndarray(i) for i in jnp.triu_indices_from(a, k))


def tril_indices_from(arr, k=0):
    a = arr._data if hasattr(arr, "_data") else jnp.asarray(arr)
    return tuple(ndarray(i) for i in jnp.tril_indices_from(a, k))


def unravel_index(indices, shape, order="C"):
    """Flat index/indices -> coordinate rows, stacked as one ndarray
    (reference numpy/multiarray.py:7876 returns the stacked form, not
    numpy's tuple)."""
    if order != "C":
        raise MXNetError("only row-major (order='C') is supported")
    idx = indices._data if hasattr(indices, "_data") else \
        jnp.asarray(indices)
    coords = jnp.unravel_index(idx, shape)
    if jnp.ndim(idx) == 0:
        return ndarray(jnp.stack([c.reshape(()) for c in coords]))
    return ndarray(jnp.stack(coords))


def fill_diagonal(a, val, wrap=False):
    """In-place on the mx.np array handle (functional rebind underneath —
    reference _npi_fill_diagonal writes in place)."""
    d = a._data
    a._data = jnp.fill_diagonal(d, jnp.asarray(
        val._data if hasattr(val, "_data") else val), wrap=wrap,
        inplace=False)
    return None


def geomspace(start, stop, num=50, endpoint=True, dtype=None, axis=0,
              ctx=None):
    return ndarray(jnp.geomspace(start, stop, num, endpoint=endpoint,
                                 dtype=_adt(dtype), axis=axis), ctx=ctx)


def unwrap(p, discont=None, axis=-1, period=6.283185307179586):
    return _invoke("unwrap",
                   lambda x: jnp.unwrap(x, discont=discont, axis=axis,
                                        period=period), [asarray(p)])


row_stack = vstack  # numpy defines row_stack as a vstack alias


def divmod(x1, x2):  # noqa: A001 - numpy API name
    return _invoke("divmod", lambda a, b: (a // b, a % b),
                   [asarray(x1), asarray(x2)], n_outputs=2)


def signbit(x):
    return _invoke("signbit", jnp.signbit, [asarray(x)])


def frexp(x):
    return _invoke("frexp", jnp.frexp, [asarray(x)], n_outputs=2)


def spacing(x):
    def fn(a):
        # numpy.spacing: ULP step AWAY from zero (negative for a < 0);
        # integer inputs promote to float like numpy; spacing(0) is the
        # smallest subnormal, which XLA's flush-to-zero arithmetic would
        # lose — special-case it as a constant
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            a = a.astype(jnp.float32)  # framework default float width
        toward = jnp.where(a >= 0, jnp.full_like(a, jnp.inf),
                           jnp.full_like(a, -jnp.inf))
        step = jnp.nextafter(a, toward) - a
        return jnp.where(a == 0, jnp.finfo(a.dtype).smallest_subnormal,
                         step)
    return _invoke("spacing", fn, [asarray(x)])
