"""mx.np.fft — discrete Fourier transforms via the XLA FFT emitter."""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .multiarray import ndarray, array, _invoke

__all__ = ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft2", "irfft2", "fftshift", "ifftshift", "fftfreq", "rfftfreq"]


def _arr(a):
    return a if isinstance(a, NDArray) else array(a)


def _fft1(name, jfn):
    def f(a, n=None, axis=-1, norm=None):
        return _invoke(name, lambda x: jfn(x, n=n, axis=axis, norm=norm),
                       [_arr(a)])
    f.__name__ = name
    return f


def _fftn(name, jfn):
    def f(a, s=None, axes=None, norm=None):
        kw = {} if axes is None and name.endswith("2") else {}
        ax = axes if axes is not None else ((-2, -1) if "2" in name else None)
        return _invoke(name, lambda x: jfn(x, s=s, axes=ax, norm=norm),
                       [_arr(a)])
    f.__name__ = name
    return f


fft = _fft1("fft", jnp.fft.fft)
ifft = _fft1("ifft", jnp.fft.ifft)
rfft = _fft1("rfft", jnp.fft.rfft)
irfft = _fft1("irfft", jnp.fft.irfft)
fft2 = _fftn("fft2", jnp.fft.fft2)
ifft2 = _fftn("ifft2", jnp.fft.ifft2)
fftn = _fftn("fftn", jnp.fft.fftn)
ifftn = _fftn("ifftn", jnp.fft.ifftn)
rfft2 = _fftn("rfft2", jnp.fft.rfft2)
irfft2 = _fftn("irfft2", jnp.fft.irfft2)


def fftshift(x, axes=None):
    return _invoke("fftshift", lambda a: jnp.fft.fftshift(a, axes), [_arr(x)])


def ifftshift(x, axes=None):
    return _invoke("ifftshift", lambda a: jnp.fft.ifftshift(a, axes),
                   [_arr(x)])


def fftfreq(n, d=1.0):
    return ndarray(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0):
    return ndarray(jnp.fft.rfftfreq(n, d))
