"""Operators that fall back to official NumPy.

Reference analog: python/mxnet/numpy/fallback.py (explicit name list)
+ numpy_op_fallback.py (CustomOp-based wrappers). TPU rebuild: one
generic host-side wrapper — convert mx arrays to host numpy, run the
official implementation, wrap array results back as ``mx.np.ndarray``
on the current context. Fallbacks are host compute: they are refused
inside ``autograd.record()`` (no gradient path, matching the
reference's recording guard, multiarray.py:339) and warn once per op
so silent CPU detours are visible.

Names already implemented natively in mx.np are NOT routed here; the
module exposes only the residual set, and `numpy/__init__.py` installs
them without shadowing native implementations.
"""
import functools
import logging

import numpy as onp

__all__ = [
    "__version__", "_NoValue", "allclose", "alltrue", "apply_along_axis",
    "apply_over_axes", "argpartition", "argwhere", "array_equal",
    "array_equiv", "choose", "compress", "corrcoef", "correlate",
    "count_nonzero", "cov", "digitize", "divmod", "dtype", "extract",
    "float_power", "frexp", "heaviside", "histogram2d",
    "histogram_bin_edges", "histogramdd", "i0", "in1d", "intersect1d",
    "isclose", "isin", "ix_", "lexsort", "min_scalar_type", "mirr",
    "modf", "msort", "nanargmax", "nanargmin", "nancumprod", "nancumsum",
    "nanmax", "nanmedian", "nanmin", "nanpercentile", "nanprod",
    "nanquantile", "ndim", "npv", "partition", "piecewise", "packbits",
    "poly", "polyadd", "polydiv", "polyfit", "polyint", "polymul",
    "polysub", "positive", "ppmt", "promote_types", "ptp", "pv", "rate",
    "real", "result_type", "roots", "searchsorted", "select",
    "setdiff1d", "setxor1d", "signbit", "size", "spacing",
    "take_along_axis", "trapz", "tril_indices_from", "trim_zeros",
    "union1d", "unpackbits", "unwrap", "vander",
]

# utilities that neither take nor return data arrays: passthrough as-is
_PASSTHROUGH = {"__version__", "_NoValue", "dtype", "promote_types",
                "result_type", "min_scalar_type"}

_WARNED = set()


def _to_onp(x):
    from ..ndarray.ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    if isinstance(x, (list, tuple)):
        return type(x)(_to_onp(v) for v in x)
    return x


def _to_mx(x):
    from .multiarray import ndarray
    if isinstance(x, onp.ndarray):
        return ndarray(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_to_mx(v) for v in x)
    if isinstance(x, dict):
        return {k: _to_mx(v) for k, v in x.items()}
    return x


def make_fallback(name, onp_func=None):
    """Build the mx-facing wrapper around an official-NumPy function."""
    fn = onp_func if onp_func is not None else getattr(onp, name)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from .. import _tape
        from ..base import MXNetError
        if _tape.is_recording():
            raise MXNetError(
                f"np.{name} is a NumPy-fallback operator (host compute, "
                "no gradient); it cannot run inside autograd.record(). "
                "Move it outside the recorded scope.")
        if name not in _WARNED:
            _WARNED.add(name)
            logging.warning(
                "np.%s is a fallback operator, using the official "
                "numpy implementation on host", name)
        out = fn(*_to_onp(args), **{k: _to_onp(v)
                                    for k, v in kwargs.items()})
        return _to_mx(out)

    wrapper.__name__ = name
    wrapper._is_np_fallback = True
    return wrapper


def _install():
    installed = []
    for name in __all__:
        if name in _PASSTHROUGH:
            if hasattr(onp, name):
                globals()[name] = getattr(onp, name)
                installed.append(name)
        elif hasattr(onp, name):
            globals()[name] = make_fallback(name)
            installed.append(name)
        # names dropped from modern numpy (msort, the financial ops)
        # simply don't install — same observable behavior as the
        # reference on a numpy without them
    return installed


_INSTALLED = _install()
