"""mx.np.linalg — NumPy-compatible linear algebra.

Reference analog: python/mxnet/numpy/linalg.py over src/operator/numpy/linalg/
(_npi.svd/inv/cholesky/... CUDA+LAPACK kernels). On TPU each lowers to the
XLA linalg emitter through jnp.linalg; all routed via the invoke funnel so
they are tape-recordable and jit-fusable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .multiarray import ndarray, array, _invoke

__all__ = ["norm", "svd", "svdvals", "inv", "pinv", "det", "slogdet",
           "eig", "eigh", "eigvals", "eigvalsh", "cholesky", "qr", "solve",
           "lstsq", "matrix_rank", "matrix_power", "multi_dot", "tensorinv",
           "tensorsolve", "cond"]


def _arr(a):
    return a if isinstance(a, NDArray) else array(a)


def norm(x, ord=None, axis=None, keepdims=False):
    return _invoke("linalg_norm",
                   lambda a: jnp.linalg.norm(a, ord=ord, axis=axis,
                                             keepdims=keepdims), [_arr(x)])


def svd(a):
    """Returns (u, l, vt) like mx.np.linalg.svd (full_matrices=False)."""
    u, s, vt = _invoke("linalg_svd",
                       lambda x: tuple(jnp.linalg.svd(x, full_matrices=False)),
                       [_arr(a)], n_outputs=3)
    return u, s, vt


def svdvals(a):
    return _invoke("linalg_svdvals",
                   lambda x: jnp.linalg.svd(x, compute_uv=False), [_arr(a)])


def inv(a):
    return _invoke("linalg_inv", jnp.linalg.inv, [_arr(a)])


def pinv(a, rcond=1e-15, hermitian=False):
    return _invoke("linalg_pinv",
                   lambda x: jnp.linalg.pinv(x, rcond=rcond,
                                             hermitian=hermitian), [_arr(a)])


def det(a):
    return _invoke("linalg_det", jnp.linalg.det, [_arr(a)])


def slogdet(a):
    return _invoke("linalg_slogdet",
                   lambda x: tuple(jnp.linalg.slogdet(x)), [_arr(a)],
                   n_outputs=2)


def eig(a):
    # XLA has no device eig for general matrices; compute on host like the
    # reference's LAPACK path (src/operator/numpy/linalg/np_eig.cc).
    import numpy as onp
    w, v = onp.linalg.eig(onp.asarray(_arr(a)._data))
    return ndarray(w.real.astype(onp.float32) if onp.isrealobj(w) or
                   onp.allclose(w.imag, 0) else w), \
        ndarray(v.real.astype(onp.float32) if onp.isrealobj(v) or
                onp.allclose(v.imag, 0) else v)


def eigh(a, UPLO="L"):
    return _invoke("linalg_eigh",
                   lambda x: tuple(jnp.linalg.eigh(x, UPLO=UPLO)), [_arr(a)],
                   n_outputs=2)


def eigvals(a):
    import numpy as onp
    w = onp.linalg.eigvals(onp.asarray(_arr(a)._data))
    if onp.isrealobj(w) or onp.allclose(w.imag, 0):
        w = w.real.astype(onp.float32)
    return ndarray(w)


def eigvalsh(a, UPLO="L"):
    return _invoke("linalg_eigvalsh",
                   lambda x: jnp.linalg.eigvalsh(x, UPLO=UPLO), [_arr(a)])


def cholesky(a):
    return _invoke("linalg_cholesky", jnp.linalg.cholesky, [_arr(a)])


def qr(a, mode="reduced"):
    return _invoke("linalg_qr",
                   lambda x: tuple(jnp.linalg.qr(x, mode=mode)), [_arr(a)],
                   n_outputs=2)


def solve(a, b):
    return _invoke("linalg_solve", jnp.linalg.solve, [_arr(a), _arr(b)])


def lstsq(a, b, rcond="warn"):
    rc = None if rcond == "warn" else rcond
    x, res, rank, sv = jnp.linalg.lstsq(_arr(a)._data, _arr(b)._data,
                                        rcond=rc)
    return ndarray(x), ndarray(res), int(rank), ndarray(sv)


def matrix_rank(M, tol=None, hermitian=False):
    return _invoke("linalg_matrix_rank",
                   lambda x: jnp.linalg.matrix_rank(x, tol), [_arr(M)])


def matrix_power(a, n):
    return _invoke("linalg_matrix_power",
                   lambda x: jnp.linalg.matrix_power(x, n), [_arr(a)])


def multi_dot(arrays):
    arrs = [_arr(a) for a in arrays]
    return _invoke("linalg_multi_dot",
                   lambda *xs: jnp.linalg.multi_dot(list(xs)), arrs)


def tensorinv(a, ind=2):
    return _invoke("linalg_tensorinv",
                   lambda x: jnp.linalg.tensorinv(x, ind=ind), [_arr(a)])


def tensorsolve(a, b, axes=None):
    return _invoke("linalg_tensorsolve",
                   lambda x, y: jnp.linalg.tensorsolve(x, y, axes=axes),
                   [_arr(a), _arr(b)])


def cond(x, p=None):
    return _invoke("linalg_cond", lambda a: jnp.linalg.cond(a, p), [_arr(x)])
