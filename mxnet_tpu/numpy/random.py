"""mx.np.random — NumPy-compatible sampling over the stateless key chain.

Reference analog: python/mxnet/numpy/random.py (_npi random kernels,
src/operator/numpy/random/). TPU design: every sampler is a pure
counter-based jax.random kernel; statefulness (numpy's global RandomState)
is emulated by the framework-wide key chain in ndarray/random.py, which is
trace-aware so samplers inside a hybridized block derive from the per-call
key (fresh randomness per step, one compiled program).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import jx_dtype
from ..ndarray.ndarray import NDArray
from ..ndarray import random as _ndrandom
from ..ops.registry import invoke_raw
from .multiarray import ndarray, array, _invoke

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "permutation", "beta", "gamma", "exponential",
           "poisson", "binomial", "multinomial", "multivariate_normal",
           "chisquare", "geometric", "gumbel", "laplace", "logistic",
           "lognormal", "pareto", "power", "rayleigh", "weibull", "f",
           "standard_normal", "standard_cauchy", "standard_exponential",
           "standard_gamma", "standard_t", "negative_binomial", "bernoulli"]

seed = _ndrandom.seed


def _size(size):
    if size is None:
        return None
    return (size,) if isinstance(size, (int, onp.integer)) else tuple(size)


def _sample(name, sampler, size, dtype=None, param_arrays=()):
    """Run a key-consuming sampler through the invoke funnel."""
    key = _ndrandom.next_key()
    arrs = [p for p in param_arrays if isinstance(p, NDArray)]

    def fn(*datas):
        return sampler(key, *datas)
    res = invoke_raw(name, fn, list(arrs), out_cls=ndarray)
    if dtype is not None and res._data.dtype != jx_dtype(dtype):
        res._data = res._data.astype(jx_dtype(dtype))
    return res


def _broadcast_shape(size, *params):
    if size is not None:
        return _size(size)
    shapes = [p.shape if isinstance(p, NDArray) else onp.shape(p)
              for p in params]
    return tuple(jnp.broadcast_shapes(*shapes)) if shapes else ()


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, out=None):
    shp = _broadcast_shape(size, low, high)
    lo = low._data if isinstance(low, NDArray) else low
    hi = high._data if isinstance(high, NDArray) else high
    res = _sample("np_uniform",
                  lambda k: jax.random.uniform(
                      k, shp, dtype=jnp.float32,
                      minval=jnp.asarray(lo, jnp.float32),
                      maxval=jnp.asarray(hi, jnp.float32)),
                  size, dtype)
    if out is not None:
        out._data = res._data
        return out
    return res


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, out=None):
    shp = _broadcast_shape(size, loc, scale)
    lo = loc._data if isinstance(loc, NDArray) else loc
    sc = scale._data if isinstance(scale, NDArray) else scale
    res = _sample("np_normal",
                  lambda k: jax.random.normal(k, shp, dtype=jnp.float32)
                  * jnp.asarray(sc, jnp.float32)
                  + jnp.asarray(lo, jnp.float32),
                  size, dtype)
    if out is not None:
        out._data = res._data
        return out
    return res


def standard_normal(size=None, dtype=None):
    return normal(0.0, 1.0, size=size, dtype=dtype)


def randn(*size):
    return normal(0.0, 1.0, size=size if size else ())


def rand(*size):
    return uniform(0.0, 1.0, size=size if size else ())


def randint(low, high=None, size=None, dtype=None, ctx=None, out=None):
    if high is None:
        low, high = 0, low
    shp = _size(size) or ()
    dt = jnp.int32 if dtype is None else jx_dtype(dtype)
    res = _sample("np_randint",
                  lambda k: jax.random.randint(k, shp, int(low), int(high),
                                               dtype=jnp.int32).astype(dt),
                  size)
    if out is not None:
        out._data = res._data
        return out
    return res


def choice(a, size=None, replace=True, p=None, ctx=None, out=None):
    shp = _size(size) or ()
    key = _ndrandom.next_key()
    if isinstance(a, (int, onp.integer)):
        pool = jnp.arange(int(a))
    else:
        pool = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    pv = None
    if p is not None:
        pv = p._data if isinstance(p, NDArray) else jnp.asarray(p)
    res = jax.random.choice(key, pool, shp, replace=replace, p=pv)
    r = ndarray(res)
    if out is not None:
        out._data = r._data
        return out
    return r


def shuffle(x):
    """In-place shuffle along the first axis (functional rebind)."""
    key = _ndrandom.next_key()
    x._data = jax.random.permutation(key, x._data, axis=0)


def permutation(x):
    key = _ndrandom.next_key()
    if isinstance(x, (int, onp.integer)):
        return ndarray(jax.random.permutation(key, int(x)))
    data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    return ndarray(jax.random.permutation(key, data, axis=0))


def beta(a, b, size=None):
    shp = _broadcast_shape(size, a, b)
    av = a._data if isinstance(a, NDArray) else a
    bv = b._data if isinstance(b, NDArray) else b
    return _sample("np_beta",
                   lambda k: jax.random.beta(
                       k, jnp.asarray(av, jnp.float32),
                       jnp.asarray(bv, jnp.float32), shp), size)


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None, out=None):
    shp = _broadcast_shape(size, shape, scale)
    sv = shape._data if isinstance(shape, NDArray) else shape
    sc = scale._data if isinstance(scale, NDArray) else scale
    res = _sample("np_gamma",
                  lambda k: jax.random.gamma(
                      k, jnp.asarray(sv, jnp.float32), shp)
                  * jnp.asarray(sc, jnp.float32), size, dtype)
    if out is not None:
        out._data = res._data
        return out
    return res


def standard_gamma(shape, size=None):
    return gamma(shape, 1.0, size=size)


def exponential(scale=1.0, size=None):
    shp = _broadcast_shape(size, scale)
    sc = scale._data if isinstance(scale, NDArray) else scale
    return _sample("np_exponential",
                   lambda k: jax.random.exponential(k, shp)
                   * jnp.asarray(sc, jnp.float32), size)


standard_exponential = exponential


def poisson(lam=1.0, size=None):
    shp = _broadcast_shape(size, lam)
    lv = lam._data if isinstance(lam, NDArray) else lam
    return _sample("np_poisson",
                   lambda k: jax.random.poisson(
                       k, jnp.asarray(lv, jnp.float32), shp).astype(
                           jnp.float32), size)


def binomial(n, p, size=None):
    shp = _broadcast_shape(size, n, p)
    nv = n._data if isinstance(n, NDArray) else n
    pv = p._data if isinstance(p, NDArray) else p

    def sampler(k):
        # sum of Bernoulli draws via uniform comparison, vectorized over n
        nmax = int(onp.max(onp.asarray(nv)))
        u = jax.random.uniform(k, (nmax,) + shp)
        counts = jnp.sum(
            (u < jnp.asarray(pv, jnp.float32))
            & (jnp.arange(nmax).reshape((nmax,) + (1,) * len(shp))
               < jnp.asarray(nv)), axis=0)
        return counts.astype(jnp.float32)
    return _sample("np_binomial", sampler, size)


def negative_binomial(n, p, size=None):
    shp = _broadcast_shape(size, n, p)
    nv = n._data if isinstance(n, NDArray) else n
    pv = p._data if isinstance(p, NDArray) else p

    def sampler(k):
        k1, k2 = jax.random.split(k)
        lam = jax.random.gamma(k1, jnp.broadcast_to(
            jnp.asarray(nv, jnp.float32), shp)) \
            * (1.0 - jnp.asarray(pv, jnp.float32)) / jnp.asarray(
                pv, jnp.float32)
        return jax.random.poisson(k2, lam, shp).astype(jnp.float32)
    return _sample("np_negative_binomial", sampler, size)


def multinomial(n, pvals, size=None):
    key = _ndrandom.next_key()
    pv = pvals._data if isinstance(pvals, NDArray) else jnp.asarray(
        pvals, jnp.float32)
    shp = _size(size) or ()
    draws = jax.random.categorical(
        key, jnp.log(jnp.maximum(pv, 1e-30)), shape=shp + (int(n),))
    out = jax.nn.one_hot(draws, pv.shape[-1], dtype=jnp.int32).sum(axis=-2)
    return ndarray(out)


def multivariate_normal(mean, cov, size=None, check_valid=None, tol=None):
    key = _ndrandom.next_key()
    m = mean._data if isinstance(mean, NDArray) else jnp.asarray(
        mean, jnp.float32)
    c = cov._data if isinstance(cov, NDArray) else jnp.asarray(
        cov, jnp.float32)
    shp = _size(size) or ()
    return ndarray(jax.random.multivariate_normal(key, m, c, shape=shp or
                                                  None))


def chisquare(df, size=None):
    return gamma(jnp.asarray(df, jnp.float32) / 2.0, 2.0, size=size) \
        if not isinstance(df, NDArray) else gamma(df / 2.0, 2.0, size=size)


def f(dfnum, dfden, size=None):
    x1 = chisquare(dfnum, size=size)
    x2 = chisquare(dfden, size=size)
    return (x1 / dfnum) / (x2 / dfden)


def standard_t(df, size=None):
    shp = _broadcast_shape(size, df)
    dv = df._data if isinstance(df, NDArray) else df
    return _sample("np_standard_t",
                   lambda k: jax.random.t(k, jnp.asarray(dv, jnp.float32),
                                          shp), size)


def standard_cauchy(size=None):
    shp = _size(size) or ()
    return _sample("np_standard_cauchy",
                   lambda k: jax.random.cauchy(k, shp), size)


def geometric(p, size=None):
    shp = _broadcast_shape(size, p)
    pv = p._data if isinstance(p, NDArray) else p
    return _sample("np_geometric",
                   lambda k: jnp.ceil(
                       jnp.log1p(-jax.random.uniform(k, shp))
                       / jnp.log1p(-jnp.asarray(pv, jnp.float32))).astype(
                           jnp.int32), size)


def gumbel(loc=0.0, scale=1.0, size=None):
    shp = _broadcast_shape(size, loc, scale)
    lo = loc._data if isinstance(loc, NDArray) else loc
    sc = scale._data if isinstance(scale, NDArray) else scale
    return _sample("np_gumbel",
                   lambda k: jax.random.gumbel(k, shp)
                   * jnp.asarray(sc, jnp.float32)
                   + jnp.asarray(lo, jnp.float32), size)


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, out=None):
    shp = _broadcast_shape(size, loc, scale)
    lo = loc._data if isinstance(loc, NDArray) else loc
    sc = scale._data if isinstance(scale, NDArray) else scale
    res = _sample("np_laplace",
                  lambda k: jax.random.laplace(k, shp)
                  * jnp.asarray(sc, jnp.float32)
                  + jnp.asarray(lo, jnp.float32), size, dtype)
    if out is not None:
        out._data = res._data
        return out
    return res


def logistic(loc=0.0, scale=1.0, size=None):
    shp = _broadcast_shape(size, loc, scale)
    lo = loc._data if isinstance(loc, NDArray) else loc
    sc = scale._data if isinstance(scale, NDArray) else scale
    return _sample("np_logistic",
                   lambda k: jax.random.logistic(k, shp)
                   * jnp.asarray(sc, jnp.float32)
                   + jnp.asarray(lo, jnp.float32), size)


def lognormal(mean=0.0, sigma=1.0, size=None):
    return exp_of_normal(mean, sigma, size)


def exp_of_normal(mean, sigma, size):
    shp = _broadcast_shape(size, mean, sigma)
    mv = mean._data if isinstance(mean, NDArray) else mean
    sv = sigma._data if isinstance(sigma, NDArray) else sigma
    return _sample("np_lognormal",
                   lambda k: jnp.exp(
                       jax.random.normal(k, shp)
                       * jnp.asarray(sv, jnp.float32)
                       + jnp.asarray(mv, jnp.float32)), size)


def pareto(a, size=None):
    shp = _broadcast_shape(size, a)
    av = a._data if isinstance(a, NDArray) else a
    return _sample("np_pareto",
                   lambda k: jax.random.pareto(
                       k, jnp.asarray(av, jnp.float32), shp) - 1.0, size)


def power(a, size=None):
    shp = _broadcast_shape(size, a)
    av = a._data if isinstance(a, NDArray) else a
    return _sample("np_power",
                   lambda k: jax.random.uniform(k, shp)
                   ** (1.0 / jnp.asarray(av, jnp.float32)), size)


def rayleigh(scale=1.0, size=None):
    shp = _broadcast_shape(size, scale)
    sc = scale._data if isinstance(scale, NDArray) else scale
    return _sample("np_rayleigh",
                   lambda k: jnp.sqrt(-2.0 * jnp.log1p(
                       -jax.random.uniform(k, shp)))
                   * jnp.asarray(sc, jnp.float32), size)


def weibull(a, size=None):
    shp = _broadcast_shape(size, a)
    av = a._data if isinstance(a, NDArray) else a
    return _sample("np_weibull",
                   lambda k: (-jnp.log1p(-jax.random.uniform(k, shp)))
                   ** (1.0 / jnp.asarray(av, jnp.float32)), size)


def bernoulli(prob=0.5, size=None, dtype=None):
    shp = _broadcast_shape(size, prob)
    pv = prob._data if isinstance(prob, NDArray) else prob
    return _sample("np_bernoulli",
                   lambda k: jax.random.bernoulli(
                       k, jnp.asarray(pv, jnp.float32), shp).astype(
                           jnp.float32 if dtype is None else jx_dtype(dtype)),
                   size)
