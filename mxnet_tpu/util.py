"""Utility flags: NumPy-semantics switches and decorators.

Reference analog: python/mxnet/util.py (np-shape/np-array global flags with
decorators). In the TPU rebuild np-shape semantics (0-dim/0-size arrays) are
always on — XLA handles them natively — so the switches mostly gate which
frontend (`mx.nd` vs `mx.np`) Gluon blocks produce.
"""
from __future__ import annotations

import functools
import threading

__all__ = ["is_np_array", "is_np_shape", "set_np", "set_np_shape", "reset_np",
           "use_np_shape", "set_module", "np_ufunc_legal_option",
           "default_array", "is_np_default_dtype", "set_np_default_dtype",
           "np_default_dtype", "use_np_default_dtype", "getenv", "setenv",
           "get_gpu_count", "get_gpu_memory", "numpy_fallback",
           "use_np", "use_np_array", "np_array", "np_shape", "wrap_np_unary_func",
           "wrap_np_binary_func", "get_cuda_compute_capability"]

_state = threading.local()


def _flags():
    if not getattr(_state, "init", False):
        _state.np_array = False
        _state.np_shape = True  # always-on: XLA supports 0-dim natively
        _state.init = True
    return _state


def is_np_array() -> bool:
    return _flags().np_array


def is_np_shape() -> bool:
    return _flags().np_shape


def set_np_shape(active: bool) -> bool:
    f = _flags()
    old, f.np_shape = f.np_shape, active
    return old


def set_np(shape: bool = True, array: bool = True, dtype: bool = False):
    f = _flags()
    f.np_shape = shape
    f.np_array = array


def reset_np():
    set_np(shape=True, array=False)


class _NumpyScope:
    def __init__(self, array: bool, shape: bool = True):
        self._array = array
        self._shape = shape

    def __enter__(self):
        f = _flags()
        self._old = (f.np_array, f.np_shape)
        f.np_array, f.np_shape = self._array, self._shape
        return self

    def __exit__(self, *exc):
        f = _flags()
        f.np_array, f.np_shape = self._old


def np_array(active: bool = True):
    return _NumpyScope(active)


def np_shape(active: bool = True):
    return _NumpyScope(_flags().np_array, active)


def use_np_array(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with _NumpyScope(True):
            return func(*args, **kwargs)
    return wrapper


def use_np(func_or_cls):
    """Decorator forcing mx.np semantics (reference util.use_np)."""
    if isinstance(func_or_cls, type):
        return func_or_cls
    return use_np_array(func_or_cls)


def wrap_np_unary_func(func):
    return func


def wrap_np_binary_func(func):
    return func


def get_cuda_compute_capability(ctx):  # compat shim; no CUDA on TPU builds
    return None


def use_np_shape(func_or_cls):
    """Decorator scoping NumPy-shape semantics (reference util.py:231).
    Scalar/zero-size shapes are always legal here (XLA-native), so the
    scope flag is informational; the decorator still flips it for code
    that inspects is_np_shape()."""
    if isinstance(func_or_cls, type):
        return func_or_cls

    @functools.wraps(func_or_cls)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func_or_cls(*args, **kwargs)
    return wrapper


def set_module(module):
    """Decorator overriding __module__ for doc rendering
    (reference util.py:312)."""
    def decorator(func):
        if module is not None:
            func.__module__ = module
        return func
    return decorator


def np_ufunc_legal_option(key, value):
    """Whether a ufunc kwarg is supported by the np dispatch layer
    (reference util.py:552)."""
    if key == "where":
        return True
    if key == "casting":
        return value in ("no", "equiv", "safe", "same_kind", "unsafe")
    if key == "order":
        return isinstance(value, str)
    if key == "dtype":
        import numpy as _onp
        try:
            _onp.dtype(value)
            return True
        except TypeError:
            return False
    if key == "subok":
        return isinstance(value, bool)
    return False


def default_array(source_array, ctx=None, dtype=None):
    """Create an array in the ACTIVE frontend: mx.np.ndarray under
    np-array semantics, classic NDArray otherwise
    (reference util.py:917)."""
    if is_np_array():
        from . import numpy as _mx_np
        return _mx_np.array(source_array, ctx=ctx, dtype=dtype)
    from .ndarray.ndarray import array as _nd_array
    return _nd_array(source_array, ctx=ctx, dtype=dtype)


def is_np_default_dtype() -> bool:
    """True when the NumPy default dtype (float64) scope is active
    (reference util.py:930)."""
    return bool(getattr(_flags(), "np_dtype", False))


def set_np_default_dtype(is_np_default_dtype=True):  # noqa: A002
    """Flip the default-dtype semantics flag; returns the previous
    value (reference util.py:940). Note: TPU arrays default to float32
    regardless (x64 is disabled for performance; documented deviation,
    docs/ENV_VARS.md)."""
    f = _flags()
    old = bool(getattr(f, "np_dtype", False))
    f.np_dtype = bool(is_np_default_dtype)
    return old


class _NumpyDtypeScope:
    def __init__(self, active=True):
        self._active = active

    def __enter__(self):
        self._old = set_np_default_dtype(self._active)
        return self

    def __exit__(self, *exc):
        set_np_default_dtype(self._old)


def np_default_dtype(active=True):
    """'with' scope for NumPy default-dtype semantics
    (reference util.py:971)."""
    return _NumpyDtypeScope(active)


def use_np_default_dtype(func_or_cls):
    """Decorator form of np_default_dtype (reference util.py:1005)."""
    if isinstance(func_or_cls, type):
        return func_or_cls

    @functools.wraps(func_or_cls)
    def wrapper(*args, **kwargs):
        with np_default_dtype(True):
            return func_or_cls(*args, **kwargs)
    return wrapper


def getenv(name):
    """Read an env var the way the runtime does (reference util.py
    getenv via MXGetEnv)."""
    import os
    return os.environ.get(name)


def setenv(name, value):
    """Set an env var for the runtime (reference util.py setenv via
    MXSetEnv). Config vars read at import time (docs/ENV_VARS.md) need
    a restart to take effect — same caveat as the reference."""
    import os
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)


def get_gpu_count():
    """Number of CUDA GPUs — always 0 on TPU builds (reference
    util.py:40)."""
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    """CUDA memory introspection has no TPU analog; raises with the
    TPU-native alternative (reference util.py:46)."""
    from .base import MXNetError
    raise MXNetError(
        "get_gpu_memory is CUDA-specific; use "
        "mx.profiler.memory_summary() / jax device memory stats for "
        "accelerator memory on this framework")


def numpy_fallback(func):
    """Decorator marking a host-numpy fallback implementation
    (reference numpy_op_fallback.register flavor): refuses under
    autograd recording and warns once, like mx.np's fallback ops."""
    from .numpy.fallback import make_fallback
    return make_fallback(getattr(func, "__name__", "fallback"), func)
