"""Utility flags: NumPy-semantics switches and decorators.

Reference analog: python/mxnet/util.py (np-shape/np-array global flags with
decorators). In the TPU rebuild np-shape semantics (0-dim/0-size arrays) are
always on — XLA handles them natively — so the switches mostly gate which
frontend (`mx.nd` vs `mx.np`) Gluon blocks produce.
"""
from __future__ import annotations

import functools
import threading

__all__ = ["is_np_array", "is_np_shape", "set_np", "set_np_shape", "reset_np",
           "use_np", "use_np_array", "np_array", "np_shape", "wrap_np_unary_func",
           "wrap_np_binary_func", "get_cuda_compute_capability"]

_state = threading.local()


def _flags():
    if not getattr(_state, "init", False):
        _state.np_array = False
        _state.np_shape = True  # always-on: XLA supports 0-dim natively
        _state.init = True
    return _state


def is_np_array() -> bool:
    return _flags().np_array


def is_np_shape() -> bool:
    return _flags().np_shape


def set_np_shape(active: bool) -> bool:
    f = _flags()
    old, f.np_shape = f.np_shape, active
    return old


def set_np(shape: bool = True, array: bool = True, dtype: bool = False):
    f = _flags()
    f.np_shape = shape
    f.np_array = array


def reset_np():
    set_np(shape=True, array=False)


class _NumpyScope:
    def __init__(self, array: bool, shape: bool = True):
        self._array = array
        self._shape = shape

    def __enter__(self):
        f = _flags()
        self._old = (f.np_array, f.np_shape)
        f.np_array, f.np_shape = self._array, self._shape
        return self

    def __exit__(self, *exc):
        f = _flags()
        f.np_array, f.np_shape = self._old


def np_array(active: bool = True):
    return _NumpyScope(active)


def np_shape(active: bool = True):
    return _NumpyScope(_flags().np_array, active)


def use_np_array(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with _NumpyScope(True):
            return func(*args, **kwargs)
    return wrapper


def use_np(func_or_cls):
    """Decorator forcing mx.np semantics (reference util.use_np)."""
    if isinstance(func_or_cls, type):
        return func_or_cls
    return use_np_array(func_or_cls)


def wrap_np_unary_func(func):
    return func


def wrap_np_binary_func(func):
    return func


def get_cuda_compute_capability(ctx):  # compat shim; no CUDA on TPU builds
    return None
