"""Live MFU gauge + anomaly watchdog, piggybacking on window retires.

The watchdog is fed from exactly one hot-path site — the dispatch
window's FIFO retire (engine.py), which is already the pipelined loop's
ONE blessed host sync — so it adds no sync of its own:

- **step time**: retire-to-retire wall time is the steady-state step
  time of a pipelined run; it feeds the ``mx_step_time_seconds``
  histogram and an EWMA gauge.
- **MFU gauge**: per-bucket FLOPs from XLA ``cost_analysis()`` on the
  already-compiled train step (``CompiledTrainStep.step_flops`` /
  ``TrainLoop.arm_mfu``) divided by measured step time, against the
  configured roofline (bench's measured or spec peak) —
  ``mx_model_mfu_ratio``.
- **NaN/inf-loss detection**: the retired payload IS the step's loss;
  once the retire has blocked for completion, reading the small loss
  buffer is one cheap device->host copy inside the already-blessed
  retire region. An episode TRANSITION (finite -> non-finite) emits
  exactly one structured ``nan_loss`` anomaly attributed to the step
  number the window tagged — not one event per poisoned step after it.
- **stall detection**: a retire whose step time exceeds
  ``MXNET_WATCHDOG_STALL_FACTOR`` x the EWMA (after a minimum sample
  count) emits one ``stall`` anomaly; the stalled sample is NOT folded
  into the EWMA, and re-arming requires a normal step, so one artificial
  stall produces exactly one event.

Anomaly events are structured dicts ``{kind, step, message, value,
time_unix}`` kept in a bounded ring (:meth:`Watchdog.anomalies`),
counted in ``mx_anomalies_total{kind=}``, and logged as one JSON line
on the ``mxnet_tpu.telemetry`` logger. Other subsystems publish their
own kinds through :meth:`Watchdog.report`/:meth:`Watchdog.episode`:
``oom`` and ``memory_budget`` (telemetry/memory.py), the
``mx_numerics_*`` divergence kinds (telemetry/numerics.py), and
``device_lost`` — a PjRt device-loss/preemption classified at the step
or retire seam (elastic/detect.py), the signal the elastic training
supervisor recovers from. Consumers that must REACT to anomalies (not
just export counts) register a callback with :meth:`Watchdog.subscribe`
— e.g. the elastic supervisor escalating repeated ``stall`` episodes
into a recovery.

Everything here is gated behind ``MXNET_TELEMETRY`` (telemetry.enabled)
at the engine call site; when telemetry is off the watchdog never runs.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional

import numpy as onp

from . import names
from .registry import default as _default_registry

__all__ = ["Watchdog", "watchdog", "stall_factor"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

#: EWMA smoothing for the reference step time
_ALPHA = 0.2
#: samples before the stall detector arms (lets compile/warmup settle)
_MIN_SAMPLES = 5
#: largest loss buffer (elements) the NaN check will fetch
_MAX_FETCH = 1 << 20


def stall_factor(default: float = 4.0) -> float:
    """``MXNET_WATCHDOG_STALL_FACTOR``: a step slower than factor x the
    EWMA step time raises a ``stall`` anomaly (docs/OBSERVABILITY.md)."""
    try:
        v = float(os.environ.get("MXNET_WATCHDOG_STALL_FACTOR", default))
    except (TypeError, ValueError):
        return default
    return v if v > 1.0 else default


class Watchdog:
    """Process-global MFU gauge + NaN/stall anomaly detector."""

    def __init__(self, max_events: int = 256):
        # bare on purpose: telemetry substrate: the deadlock episode fires under it
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        self._events: "deque[dict]" = deque(maxlen=max_events)
        self._ewma: Optional[float] = None
        self._samples = 0
        self._nan_active = False
        self._stall_active = False
        # external episodic kinds (memory_budget, ...): kind -> active
        self._episode_active: dict = {}
        # anomaly-channel subscribers: callback(event_dict)
        self._subscribers: list = []
        self._flops: Optional[float] = None
        self._peak: Optional[float] = None
        reg = _default_registry()
        self._c_anom = reg.counter(names.ANOMALIES, label_key="kind")
        self._h_step = reg.histogram(names.STEP_TIME_SECONDS)
        self._g_ewma = reg.gauge(names.STEP_TIME_EWMA)
        self._g_flops = reg.gauge(names.MODEL_FLOPS_PER_STEP)
        self._g_fps = reg.gauge(names.MODEL_FLOPS_PER_SEC)
        self._g_mfu = reg.gauge(names.MFU)

    # ---------------- configuration ----------------
    def set_model_flops(self, flops_per_step: float):
        """Arm the MFU numerator: XLA cost_analysis FLOPs of the ONE
        compiled program the chip runs per step."""
        with self._lock:
            self._flops = float(flops_per_step)
        self._g_flops.set(float(flops_per_step))

    def set_peak_flops(self, peak_flops_per_sec: float):
        """Arm the MFU denominator: the roofline in FLOP/s (bench's
        measured matmul roofline, or the chip's spec peak)."""
        with self._lock:
            self._peak = float(peak_flops_per_sec)

    @property
    def model_flops(self) -> Optional[float]:
        return self._flops

    @property
    def peak_flops(self) -> Optional[float]:
        return self._peak

    # ---------------- the retire hook ----------------
    def observe_retire(self, step, payload=None,
                       dt: Optional[float] = None):
        """Called at each window retire (AFTER the blocking sync, inside
        the blessed ``allow_transfers`` region). ``dt`` is the
        retire-to-retire wall time (None on a window's first retire);
        ``payload`` is the retired async result — inspected for
        NaN/inf when it looks like a small float loss buffer."""
        if dt is not None and dt > 0:
            self._observe_step_time(step, dt)
        if payload is not None:
            self._check_finite(step, payload)

    def _observe_step_time(self, step, dt: float):
        self._h_step.observe(dt)
        with self._lock:
            ewma, samples = self._ewma, self._samples
        factor = stall_factor()
        if ewma is not None and samples >= _MIN_SAMPLES \
                and dt > factor * ewma:
            with self._lock:
                fire = not self._stall_active
                self._stall_active = True
            if fire:
                self._anomaly(
                    "stall", step, value=dt,
                    message=f"step {step} took {dt*1e3:.1f}ms, "
                            f"> {factor:g}x the {ewma*1e3:.1f}ms EWMA "
                            "step time")
            # the stalled sample is NOT folded into the EWMA: the
            # reference step time must not chase the pathology
        else:
            with self._lock:
                self._stall_active = False
                self._ewma = dt if self._ewma is None else \
                    (1 - _ALPHA) * self._ewma + _ALPHA * dt
                self._samples += 1
                ewma = self._ewma
                flops, peak = self._flops, self._peak
            self._g_ewma.set(ewma)
            if flops:
                fps = flops / dt
                self._g_fps.set(fps)
                if peak:
                    self._g_mfu.set(fps / peak)

    def _check_finite(self, step, payload):
        arr = getattr(payload, "_data", payload)   # NDArray -> jax.Array
        dtype = getattr(arr, "dtype", None)
        if dtype is None or getattr(arr, "size", _MAX_FETCH + 1) \
                > _MAX_FETCH:
            return
        try:
            if not onp.issubdtype(onp.dtype(dtype), onp.floating):
                return
            # the retire already blocked for completion; this is one
            # small device->host copy inside the blessed retire region
            finite = bool(onp.isfinite(onp.asarray(arr)).all())
        except Exception:           # exotic payloads: never kill a run
            return
        with self._lock:
            fire = not finite and not self._nan_active
            self._nan_active = not finite
        if fire:
            self._anomaly(
                "nan_loss", step, value=None,
                message=f"non-finite loss first observed at step {step}")

    # ---------------- events ----------------
    def report(self, kind: str, step, message: str, value=None) -> dict:
        """Emit one structured anomaly event on the watchdog channel —
        the SAME ring/counter/log-line path the built-in NaN and stall
        detectors use. Other subsystems (the memory watchdog, OOM
        forensics) publish through here so every anomaly, whatever its
        source, lands in ``anomalies()``, ``mx_anomalies_total{kind=}``
        and one ``mx-anomaly`` JSON log line. For a CONDITION (vs a
        one-shot event) use :meth:`episode` to get exactly-one-per-
        episode semantics."""
        evt = {"kind": kind, "step": step, "message": message,
               "value": value, "time_unix": time.time()}
        with self._lock:
            self._events.append(evt)
            subs = list(self._subscribers)
        self._c_anom.inc(label=kind)
        _LOG.warning("mx-anomaly %s", json.dumps(evt))
        for cb in subs:
            try:
                cb(evt)
            except Exception:    # pragma: no cover - a subscriber must
                _LOG.warning("anomaly subscriber %r failed", cb,
                             exc_info=True)   # never kill the reporter
        return evt

    _anomaly = report

    # ---------------- subscription ----------------
    def subscribe(self, callback):
        """Register ``callback(event_dict)`` to run on EVERY anomaly the
        channel reports (whatever its source subsystem) — the reactive
        half of the channel, e.g. the elastic supervisor escalating
        stall episodes into a recovery. Callbacks run synchronously on
        the reporting thread and must be cheap + non-raising (exceptions
        are logged and swallowed). Returns ``callback`` for symmetric
        :meth:`unsubscribe`."""
        with self._lock:
            if callback not in self._subscribers:
                self._subscribers.append(callback)
        return callback

    def unsubscribe(self, callback):
        with self._lock:
            if callback in self._subscribers:
                self._subscribers.remove(callback)

    def episode(self, kind: str, active: bool, step=None,
                message: str = "", value=None) -> bool:
        """Episode-transition reporting for external detectors: fires
        :meth:`report` exactly ONCE when ``kind`` goes inactive->active
        (the memory-budget discipline — a run sitting over budget for
        1000 steps produces one event, not 1000); recovery re-arms.
        Returns True when an event was emitted."""
        with self._lock:
            fire = bool(active) and not self._episode_active.get(kind)
            self._episode_active[kind] = bool(active)
        if fire:
            self.report(kind, step, message=message, value=value)
        return fire

    def anomalies(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if kind is None else [e for e in evs
                                         if e["kind"] == kind]

    def reset(self):
        with self._lock:
            self._events.clear()
            self._ewma = None
            self._samples = 0
            self._nan_active = False
            self._stall_active = False
            self._episode_active.clear()
            self._subscribers.clear()
            self._flops = None
            self._peak = None


_watchdog = Watchdog()


def watchdog() -> Watchdog:
    """The process-global watchdog (``mx.telemetry.watchdog()``)."""
    return _watchdog
