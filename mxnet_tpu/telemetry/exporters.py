"""Telemetry exporters: JSON snapshot, Prometheus text format, heartbeat.

Three pluggable ways out of the metrics registry (docs/OBSERVABILITY.md):

- :func:`snapshot` — one JSON-serializable dict of every series
  (schema-stable: tests pin the top-level keys), for BENCH legs,
  ``tools/diagnose.py --telemetry``, and ad-hoc dumps;
- :func:`prometheus_text` / :func:`write_prometheus` — Prometheus
  exposition format (``# HELP``/``# TYPE``, ``_bucket{le=}``/``_sum``/
  ``_count`` histograms), written atomically to
  ``MXNET_PROMETHEUS_FILE`` for a node-exporter textfile collector or
  any scraper that reads files;
- :class:`Heartbeat` — a daemon thread that logs one structured JSON
  line per ``MXNET_TELEMETRY_HEARTBEAT_SEC`` interval (and refreshes the
  Prometheus file when configured), so a headless run leaves a
  greppable pulse in its logs.

Registry collectors run before every export, so pull-model series
(compile-cache state) are fresh.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from ..analysis.threads import mx_lock
from ..base import MXNetError
from . import names
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       default as _default_registry)
from .watchdog import watchdog as _watchdog

__all__ = ["SCHEMA_VERSION", "snapshot", "prometheus_text",
           "write_prometheus", "prometheus_file", "Heartbeat",
           "start_heartbeat", "stop_heartbeat", "heartbeat_interval"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

#: bump ONLY with a documented migration; tests pin the snapshot schema
SCHEMA_VERSION = 1


def prometheus_file() -> Optional[str]:
    """``MXNET_PROMETHEUS_FILE`` (None when unset)."""
    return os.environ.get("MXNET_PROMETHEUS_FILE") or None


def heartbeat_interval() -> float:
    """``MXNET_TELEMETRY_HEARTBEAT_SEC`` (0 = heartbeat off)."""
    try:
        return max(0.0, float(
            os.environ.get("MXNET_TELEMETRY_HEARTBEAT_SEC", "0")))
    except (TypeError, ValueError):
        return 0.0


# ---------------------------------------------------------------------------
# JSON snapshot
# ---------------------------------------------------------------------------

def _metric_values(m):
    """Flatten an unlabeled metric to its scalar, keep labeled ones as
    {label: value}."""
    vals = m.values()
    if m.label_key is None:
        return vals.get("", 0.0 if isinstance(m, Counter) else None)
    return dict(sorted(vals.items()))


def snapshot(registry: Optional[MetricsRegistry] = None) -> dict:
    """One JSON-serializable dict of the full telemetry state:

    ``{"schema_version", "time_unix", "counters", "gauges",
    "histograms", "anomalies"}`` — unlabeled series map name -> value,
    labeled ones name -> {label: value}, histograms name -> (slot or
    {label: slot}) where a slot is ``{count, sum, p50, p99, buckets}``.
    """
    reg = registry if registry is not None else _default_registry()
    counters, gauges, hists = {}, {}, {}
    for m in reg.collect():
        if isinstance(m, Histogram):
            if m.label_key is None:
                hists[m.name] = m.snapshot_slot()
            else:
                hists[m.name] = {lb: m.snapshot_slot(lb)
                                 for lb in m.labels()}
        elif isinstance(m, Counter):
            counters[m.name] = _metric_values(m)
        elif isinstance(m, Gauge):
            gauges[m.name] = _metric_values(m)
    wd = _watchdog()
    events = wd.anomalies()
    return {
        "schema_version": SCHEMA_VERSION,
        "time_unix": time.time(),
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "anomalies": {"count": len(events), "recent": events[-16:]},
    }


# ---------------------------------------------------------------------------
# Prometheus text format
# ---------------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v != v:                       # pragma: no cover - NaN guard
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(key: Optional[str], value: Optional[str],
               extra: str = "") -> str:
    parts = []
    if key is not None and value is not None and value != "":
        parts.append(f'{key}="{value}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry in Prometheus exposition format, deterministically
    ordered (sorted names, sorted label values) so exports diff and the
    golden test stays stable."""
    reg = registry if registry is not None else _default_registry()
    lines = []
    for m in reg.collect():
        lines.append(f"# HELP {m.name} {m.help or m.name}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            labels = m.labels() if m.label_key is not None else [None]
            for lb in labels:
                slot = m.snapshot_slot(lb)
                if slot is None:
                    slot = {"count": 0, "sum": 0.0,
                            "buckets": {"+Inf": 0}}
                for le, cum in slot["buckets"].items():
                    ls = _label_str(m.label_key, lb, f'le="{le}"')
                    lines.append(f"{m.name}_bucket{ls} {cum}")
                ls = _label_str(m.label_key, lb)
                lines.append(f"{m.name}_sum{ls} {_fmt(slot['sum'])}")
                lines.append(f"{m.name}_count{ls} {slot['count']}")
        else:
            vals = m.values()
            if not vals and isinstance(m, Counter) \
                    and m.label_key is None:
                vals = {"": 0.0}
            for lb in sorted(vals):
                ls = _label_str(m.label_key, lb or None)
                lines.append(f"{m.name}{ls} {_fmt(vals[lb])}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: Optional[str] = None,
                     registry: Optional[MetricsRegistry] = None) -> str:
    """Atomically write :func:`prometheus_text` to ``path`` (default
    ``MXNET_PROMETHEUS_FILE``); returns the path written."""
    path = path or prometheus_file()
    if not path:
        raise MXNetError(
            "write_prometheus: no path given and MXNET_PROMETHEUS_FILE "
            "is unset (docs/OBSERVABILITY.md)")
    text = prometheus_text(registry)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------

def _heartbeat_payload() -> dict:
    """The condensed per-beat line: headline counters/gauges + anomaly
    count (full series belong in the Prometheus file, not the log)."""
    reg = _default_registry()
    wd = _watchdog()
    keys = (names.TRAIN_STEPS, names.WINDOW_RETIRES, names.HOST_SYNCS,
            names.PREFETCH_STARVATION, names.COMPILE_RETRACES,
            names.CHECKPOINT_SAVES)
    out = {"time_unix": time.time()}
    for k in keys:
        m = reg.get(k)
        if m is None:
            continue
        out[k] = _metric_values(m)
    for k in (names.STEP_TIME_EWMA, names.MFU,
              names.MODEL_FLOPS_PER_SEC, names.NUMERICS_GRAD_NORM,
              names.NUMERICS_PARAM_NORM):
        g = reg.get(k)
        v = g.value() if g is not None else None
        if v is not None:
            out[k] = v
    out["anomalies"] = len(wd.anomalies())
    return out


class Heartbeat:
    """Daemon thread emitting one structured-log telemetry line per
    interval; also refreshes ``MXNET_PROMETHEUS_FILE`` when set."""

    def __init__(self, interval: Optional[float] = None,
                 write_file: bool = True):
        self.interval = heartbeat_interval() if interval is None \
            else float(interval)
        if self.interval <= 0:
            raise MXNetError(
                "Heartbeat needs a positive interval (set "
                "MXNET_TELEMETRY_HEARTBEAT_SEC or pass interval=)")
        self._write_file = write_file
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="mx-telemetry-heartbeat", daemon=True)
        self._counter = _default_registry().counter(names.HEARTBEATS)
        self.beats = 0
        # serializes beat() between the daemon thread and any caller
        # (atexit flush, tests); also guards the terminal _stopped flag,
        # so a stop() landing mid-beat waits the beat out instead of
        # racing it into a second MXNET_PROMETHEUS_FILE write
        self._beat_mu = mx_lock("telemetry.heartbeat.beat")
        self._stopped = False

    def start(self) -> "Heartbeat":
        if self._stopped:
            raise MXNetError(
                "Heartbeat.start: this heartbeat was stopped; threads "
                "cannot be restarted — build a new Heartbeat()")
        _install_atexit()   # short runs still flush a final snapshot
        if not self._thread.is_alive():
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def beat(self):
        """One heartbeat: log the condensed payload, bump the counter,
        refresh the Prometheus file when configured. Serialized against
        concurrent callers and a no-op once :meth:`stop` has landed, so
        the final flush never doubles up with an in-flight beat."""
        with self._beat_mu:
            if self._stopped:
                return
            try:
                payload = _heartbeat_payload()
                _LOG.info("mx-telemetry %s", json.dumps(payload))
                self._counter.inc()
                self.beats += 1
                if self._write_file and prometheus_file():
                    write_prometheus()
            except Exception:        # a heartbeat must never kill a run
                _LOG.warning("telemetry heartbeat failed", exc_info=True)

    def stop(self, timeout: float = 5.0):
        """Signal shutdown and join the thread (idempotent).

        Acquiring the beat lock first means an in-flight beat finishes
        (or the next one sees ``_stopped`` and bails) before we join —
        and the join itself happens with no lock held."""
        self._stop.set()
        with self._beat_mu:
            self._stopped = True
        if self._thread.is_alive():
            self._thread.join(timeout)

    @property
    def running(self) -> bool:
        return self._thread.is_alive()


_active_heartbeat: Optional[Heartbeat] = None
_hb_lock = mx_lock("telemetry.heartbeat")
_atexit_installed = False


def _install_atexit():
    global _atexit_installed
    if not _atexit_installed:
        import atexit
        atexit.register(_atexit_flush)
        _atexit_installed = True


def _atexit_flush():
    """Final exporter flush at interpreter exit: a short run that exits
    before the first heartbeat interval (or between intervals) still
    leaves one last structured log line and a final
    ``MXNET_PROMETHEUS_FILE`` snapshot on disk — a scraper never reads
    a stale or absent file because the process was brief. With no
    heartbeat running, a configured Prometheus file is still refreshed.
    Never raises (exit paths must stay clean)."""
    with _hb_lock:
        hb = _active_heartbeat
    try:
        if hb is not None and hb.running:
            hb.beat()
            hb.stop()
        elif prometheus_file():
            write_prometheus()
    except Exception:            # pragma: no cover - defensive
        _LOG.warning("telemetry atexit flush failed", exc_info=True)


def start_heartbeat(interval: Optional[float] = None,
                    write_file: bool = True) -> Heartbeat:
    """Start (or return the already-running) process heartbeat."""
    global _active_heartbeat
    with _hb_lock:
        if _active_heartbeat is not None and _active_heartbeat.running:
            return _active_heartbeat
        _active_heartbeat = Heartbeat(interval=interval,
                                      write_file=write_file).start()
        return _active_heartbeat


def stop_heartbeat():
    """Stop the process heartbeat if one is running (idempotent)."""
    global _active_heartbeat
    with _hb_lock:
        hb, _active_heartbeat = _active_heartbeat, None
    if hb is not None:
        hb.stop()


# the flush re-checks configuration at exit time (env may be set after
# import), so installing unconditionally is a no-op for unconfigured
# processes and a final-snapshot guarantee for configured ones
_install_atexit()
