"""Training-numerics observability: in-program grad/param health,
divergence watchdog, NaN-origin forensics.

PR 6 gave the runtime the TIME domain (step timeline, MFU watchdog) and
PR 7 the SPACE domain (HBM census, OOM forensics); this module is the
NUMERICS domain — whether the training run is mathematically healthy,
measured inside the compiled step (docs/OBSERVABILITY.md "numerics"):

1. **In-program health statistics.** ``Trainer.compile_step(numerics=)``
   (env ``MXNET_NUMERICS=off|global|per_layer``) threads auxiliary
   on-device outputs through the fused/ZeRO train step: global grad
   norm, param norm, update/weight ratio, per-dtype non-finite counts,
   and (``per_layer``) a per-parameter grad-norm vector. All statistics
   are reductions of the values the program already computes, composed
   on the dp mesh by GSPMD — under the ZeRO sharded update
   (arXiv:2004.13336) the norms are computed from each replica's flat
   1/N shard and psum'd, so every replica reports the TRUE global norm
   without materializing a replicated gradient. Host-side recomputation
   would be both wrong (it sees one replica) and a transfer-guard
   violation; in-program aux outputs are the TensorFlow-paper answer
   (arXiv:1605.08695) of treating numeric health checks as first-class
   runtime instrumentation.

2. **Sync-free retirement.** The aux scalars ride the async dispatch
   window alongside the loss (:class:`StepNumerics`); the
   :class:`NumericsMonitor` reads them at the window's existing blessed
   retire — the step's program has already completed by then, so the
   tiny host copies add no stall and no unblessed sync
   (``MXNET_TRANSFER_GUARD=raise`` stays clean).

3. **Divergence watchdog.** Episode-semantics anomalies through the
   PR 6 watchdog channel — each fires exactly once per episode:
   ``grad_spike`` (norm > ``MXNET_GRADNORM_SPIKE_FACTOR`` x EWMA),
   ``nonfinite_grad`` (any non-finite gradient element),
   ``update_ratio`` (||dw||/||w|| out of band vs its own EWMA), and
   ``master_drift`` (bf16 master-vs-weight drift beyond
   ``MXNET_MASTER_DRIFT_TOL``). The eager NaN guard
   (``inspector.install_nan_guard``) reports ``nonfinite_eager``
   through the same channel.

4. **NaN-origin forensics.** When ``nonfinite_grad`` fires, a one-shot
   re-execution of the failing shape bucket on the CAPTURED input batch
   runs outside the hot loop under ``jax.debug_nans``/``debug_infs``
   (:func:`localize_nonfinite`), localizing the first primitive that
   produced a non-finite value, and an atomic ranked post-mortem JSON
   (schema v1, mirroring the PR 7 OOM dump) is written to
   ``MXNET_NUMERICS_DUMP_DIR``: offending op, per-layer norm table,
   lr/loss-scale/step context, sizing hints.

Cost model: ``global`` mode adds a handful of scalar reductions to the
compiled program (sub-percent on real models) and must be bit-exact on
params/loss vs ``off`` — the statistics only ADD consumers of values
the update already computes. ``per_layer`` additionally consumes each
parameter's logical (unsharded) gradient, which under ZeRO can force
XLA to materialize the full gradient it would otherwise reduce-scatter
away — budget a few percent and use it for debugging, not steady state.
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from . import names
from .registry import default as _default_registry
from .watchdog import watchdog as _watchdog

__all__ = ["mode", "spike_factor", "master_drift_tol", "dump_dir",
           "DUMP_SCHEMA_VERSION", "TOP_K_LAYERS", "sumsq",
           "nonfinite_count", "StepNumerics", "NumericsMonitor",
           "monitor", "localize_nonfinite", "write_dump"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

#: schema of the numerics post-mortem dump (golden-tested)
DUMP_SCHEMA_VERSION = 1

#: per-layer gauge series published per retire (largest norms first);
#: bounded well under names.MAX_LABEL_VALUES
TOP_K_LAYERS = 16

#: samples before the spike/ratio detectors arm (warmup transients)
_MIN_SAMPLES = 5

#: EWMA smoothing for the grad-norm / update-ratio references
_ALPHA = 0.2

_EPS = 1e-12

#: update/weight-ratio histogram buckets (log-spaced; healthy training
#: sits around 1e-3..1e-2)
RATIO_BUCKETS = (1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 3e-3, 1e-2, 3e-2,
                 1e-1, 0.3, 1.0)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def mode(requested: Optional[str] = None) -> Optional[str]:
    """Normalize the ``numerics=`` kwarg / ``MXNET_NUMERICS`` env value
    to one of ``None`` (off) | ``'global'`` | ``'per_layer'``."""
    v = requested if requested is not None \
        else os.environ.get("MXNET_NUMERICS")
    if v is None or v is False:
        return None
    if v is True:
        return "global"
    v = str(v).strip().lower().replace("-", "_")
    if v in ("", "0", "off", "false", "no", "none"):
        return None
    if v in ("1", "on", "global", "true"):
        return "global"
    if v in ("per_layer", "layer", "layers", "2"):
        return "per_layer"
    _LOG.warning("unknown MXNET_NUMERICS mode %r; treating as 'global'",
                 v)
    return "global"


def spike_factor(default: float = 10.0) -> float:
    """``MXNET_GRADNORM_SPIKE_FACTOR``: a retired grad norm above
    factor x its EWMA raises a ``grad_spike`` anomaly (the same
    threshold gates the update-ratio band)."""
    try:
        v = float(os.environ.get("MXNET_GRADNORM_SPIKE_FACTOR", default))
    except (TypeError, ValueError):
        return default
    return v if v > 1.0 else default


def master_drift_tol(default: float = 1e-2) -> float:
    """``MXNET_MASTER_DRIFT_TOL``: max tolerated relative drift between
    an fp32 master shard and its low-precision weight cast before a
    ``master_drift`` anomaly fires."""
    try:
        v = float(os.environ.get("MXNET_MASTER_DRIFT_TOL", default))
    except (TypeError, ValueError):
        return default
    return v if v > 0 else default


def dump_dir() -> Optional[str]:
    """``MXNET_NUMERICS_DUMP_DIR`` (None = no post-mortem files; the
    ``nonfinite_grad`` anomaly still fires)."""
    return os.environ.get("MXNET_NUMERICS_DUMP_DIR") or None


# ---------------------------------------------------------------------------
# traced helpers (used inside the compiled step program)
# ---------------------------------------------------------------------------

def sumsq(x):
    """Sum of squares in f32 — on a NamedSharding-sharded array GSPMD
    lowers this to a shard-local reduction + psum on the mesh axes, so
    the result is the exact GLOBAL statistic on every replica."""
    return jnp.sum(jnp.square(x.astype(jnp.float32)))


def nonfinite_count(x):
    """Count of non-finite elements (i32); sharded arrays psum-compose
    exactly like :func:`sumsq`. Zero padding (ZeRO flat shards) is
    finite and never inflates the count."""
    return jnp.sum(~jnp.isfinite(x)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# the per-step aux record riding the dispatch window
# ---------------------------------------------------------------------------

class StepNumerics:
    """One step's on-device numerics aux, pushed into the dispatch
    window alongside the loss and read back at the blessed retire.

    ``raw`` holds the small device scalars the compiled step returned
    (async futures until the retire blocks); ``forensic`` is the
    step's one-shot NaN-origin re-execution closure (captured input
    batch + RNG key, current params); ``context`` is the host-side
    lr/loss-scale/step snapshot taken at dispatch.
    """

    __slots__ = ("mode", "raw", "param_names", "context", "forensic",
                 "_vals")

    def __init__(self, mode: str, raw: Dict[str, Any],
                 param_names: List[str], context: dict,
                 forensic: Optional[Callable] = None):
        self.mode = mode
        self.raw = raw
        self.param_names = list(param_names)
        self.context = dict(context or {})
        self.forensic = forensic
        self._vals: Optional[dict] = None

    def host_values(self) -> dict:
        """Host view of the aux: derived norms/ratios/counts. One small
        device->host copy per scalar — call at (or after) the retire,
        when the step's program has already completed."""
        if self._vals is not None:
            return self._vals
        raw = self.raw

        def f(key):
            return float(onp.asarray(raw[key], dtype="float64"))

        gsq, psq, usq = f("grad_sq"), f("param_sq"), f("upd_sq")
        pnorm = math.sqrt(max(psq, 0.0)) if math.isfinite(psq) else psq
        vals = {
            "grad_norm": _safe_sqrt(gsq),
            "param_norm": pnorm,
            "update_norm": _safe_sqrt(usq),
            "update_ratio": _safe_sqrt(usq) / (pnorm + _EPS)
            if math.isfinite(pnorm) else float("nan"),
            "nonfinite": {dt: int(onp.asarray(c))
                          for dt, c in raw["nonfinite"].items()},
        }
        vals["nonfinite_total"] = sum(vals["nonfinite"].values())
        if "master_drift" in raw:
            vals["master_drift"] = f("master_drift")
        if "layer_grad_sq" in raw:
            lsq = onp.asarray(raw["layer_grad_sq"], dtype="float64")
            vals["layer_grad_norm"] = {
                name: _safe_sqrt(float(v))
                for name, v in zip(self.param_names, lsq)}
        self._vals = vals
        return vals


def _safe_sqrt(v: float) -> float:
    return math.sqrt(v) if math.isfinite(v) and v >= 0 else float(v)


# ---------------------------------------------------------------------------
# NaN-origin localization
# ---------------------------------------------------------------------------

def localize_nonfinite(thunk: Callable[[], Any]) -> Optional[str]:
    """Run ``thunk`` (the captured failing computation) with
    ``jax_debug_nans`` + ``jax_debug_infs`` armed: every primitive's
    concrete output is checked and the FIRST one producing a non-finite
    value raises ``FloatingPointError`` naming that primitive — the
    NaN's origin. Returns the description string, ``None`` when the
    re-execution stayed finite (the failure did not reproduce), or an
    error note when the re-execution itself failed. Strictly a
    debugging path: run it OUTSIDE the hot loop."""
    old_nan = jax.config.jax_debug_nans
    old_inf = jax.config.jax_debug_infs
    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_debug_infs", True)
    try:
        thunk()
        return None
    except FloatingPointError as e:
        # keep the headline ("invalid value (inf) encountered in
        # jit(exp)") and drop jax's multi-paragraph remediation advice
        return str(e).split(". Because", 1)[0].split("\n", 1)[0]
    except Exception as e:       # pragma: no cover - defensive
        return f"re-execution failed: {type(e).__name__}: {e}"
    finally:
        jax.config.update("jax_debug_nans", old_nan)
        jax.config.update("jax_debug_infs", old_inf)


# ---------------------------------------------------------------------------
# post-mortem dump
# ---------------------------------------------------------------------------

def _json_safe(v):
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    if isinstance(v, (onp.floating, onp.integer)):
        return _json_safe(v.item())
    return str(v)


def write_dump(payload: dict) -> Optional[str]:
    """Write one numerics post-mortem JSON atomically (the same
    tmp+fsync+os.replace helper ``nd.save`` and the OOM dump writer
    use) to ``MXNET_NUMERICS_DUMP_DIR``; returns the path or None when
    the dir is unset."""
    d = dump_dir()
    if not d:
        return None
    from ..checkpoint.atomic import atomic_write_bytes
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"mx_numerics_{int(time.time())}_{os.getpid()}.json")
    data = json.dumps(payload, indent=1, default=_json_safe).encode()
    atomic_write_bytes(path, data, fault="numerics.dump")
    return path


def _divergence_hints(vals: dict, context: dict) -> List[str]:
    """Actionable knobs, ranked by what the statistics implicate."""
    hints = []
    nf = vals.get("nonfinite", {})
    low_prec = [dt for dt, n in nf.items()
                if n and dt in ("bfloat16", "float16")]
    if low_prec:
        hints.append(
            f"non-finite gradients in {'/'.join(low_prec)} params: "
            "enable multi_precision fp32 masters and/or dynamic loss "
            "scaling (mx.amp), or raise MXNET_ZERO_SHARD_MIN_SIZE=0 so "
            "masters shard (docs/PERF_NOTES.md)")
    lr = context.get("learning_rate")
    ratio = vals.get("update_ratio")
    if ratio is not None and math.isfinite(ratio) and ratio > 0.1:
        hints.append(
            f"update/weight ratio {ratio:.3g} is large: the step is "
            "rewriting the weights — lower the learning rate"
            + (f" (currently {lr})" if lr is not None else "")
            + " or add warmup")
    if context.get("clip_gradient") in (None, 0.0):
        hints.append(
            "no gradient clipping configured: set clip_gradient on the "
            "optimizer to bound spikes while you bisect the cause")
    if context.get("loss_scale") not in (None, 1.0):
        hints.append(
            f"AMP loss scale is {context.get('loss_scale')}: an "
            "overflowing scale poisons gradients before the unscale — "
            "check the scaler's backoff window")
    hints.append(
        "re-run the failing batch under MXNET_INSPECT_NAN=1 (eager "
        "per-op NaN guard) to confirm the offending op interactively")
    return hints


# ---------------------------------------------------------------------------
# the monitor: gauges, episodes, forensics trigger
# ---------------------------------------------------------------------------

class NumericsMonitor:
    """Process-global numerics observer, fed from the dispatch window's
    blessed retire (``engine.DispatchWindow``) — or directly via
    ``CompiledTrainStep.numerics_values()`` for windowless callers."""

    def __init__(self):
        # bare on purpose: telemetry substrate: the audit's metrics path runs under it
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        self._ewma_g: Optional[float] = None
        self._n_g = 0
        self._ewma_r: Optional[float] = None
        self._n_r = 0
        self._active: Dict[str, bool] = {}
        self._last: Optional[dict] = None
        reg = _default_registry()
        self._g_gnorm = reg.gauge(names.NUMERICS_GRAD_NORM)
        self._g_pnorm = reg.gauge(names.NUMERICS_PARAM_NORM)
        self._g_ewma = reg.gauge(names.NUMERICS_GRAD_NORM_EWMA)
        self._g_drift = reg.gauge(names.NUMERICS_MASTER_DRIFT)
        self._g_layer = reg.gauge(names.NUMERICS_LAYER_GRAD_NORM,
                                  label_key="param")
        self._h_ratio = reg.histogram(names.NUMERICS_UPDATE_RATIO,
                                      buckets=RATIO_BUCKETS)
        self._c_nonfinite = reg.counter(names.NUMERICS_NONFINITE,
                                        label_key="dtype")
        self._c_dumps = reg.counter(names.NUMERICS_DUMPS)

    # ---------------- the retire hook ----------------
    def observe_retire(self, step, rec: StepNumerics) -> Optional[dict]:
        """Consume one step's aux record at its window retire: publish
        the ``mx_numerics_*`` series, run the divergence detectors
        (exactly one anomaly per episode), and on a fresh non-finite
        episode run the NaN-origin forensics + dump. Never raises —
        observability must not kill a run."""
        try:
            return self._observe(step, rec)
        except Exception:        # pragma: no cover - defensive
            _LOG.warning("numerics retire observation failed",
                         exc_info=True)
            return None

    def _observe(self, step, rec: StepNumerics) -> dict:
        from ..analysis import guard as _tguard
        # the step's program completed at the retire sync; these reads
        # are the designed, blessed device->host copies numerics adds
        with _tguard.allow_transfers("numerics retire read"):
            vals = rec.host_values()
        gn, pn = vals["grad_norm"], vals["param_norm"]
        ratio = vals["update_ratio"]
        nf_total = vals["nonfinite_total"]
        self._g_gnorm.set(gn)
        self._g_pnorm.set(pn)
        for dt, n in vals["nonfinite"].items():
            if n:
                self._c_nonfinite.inc(n, label=dt)
        if math.isfinite(ratio):
            self._h_ratio.observe(ratio)
        if "master_drift" in vals:
            self._g_drift.set(vals["master_drift"])
        layers = vals.get("layer_grad_norm")
        if layers:
            top = sorted(layers.items(),
                         key=lambda kv: -_finite_or_inf(kv[1]))
            for name, v in top[:TOP_K_LAYERS]:
                self._g_layer.set(v, label=name)

        wd = _watchdog()
        # non-finite gradients: one anomaly + one forensic dump per
        # episode; the anomaly message names the offending op and dump
        if self._transition("nonfinite_grad", nf_total > 0):
            path, op = self._run_forensics(step, rec, vals)
            counts = ", ".join(f"{dt}:{n}" for dt, n
                               in sorted(vals["nonfinite"].items()) if n)
            msg = (f"non-finite gradient first observed at step {step} "
                   f"({counts or nf_total} non-finite elements)")
            if op:
                msg += f"; origin: {op}"
            msg += (f"; post-mortem dump: {path}" if path else
                    "; set MXNET_NUMERICS_DUMP_DIR for a ranked "
                    "post-mortem dump")
            wd.report("nonfinite_grad", step, message=msg,
                      value=nf_total)

        # grad-norm spike: EWMA-relative, spiking samples not folded in
        factor = spike_factor()
        if math.isfinite(gn):
            with self._lock:
                ewma, n = self._ewma_g, self._n_g
            spike = (ewma is not None and n >= _MIN_SAMPLES
                     and gn > factor * ewma)
            if self._transition("grad_spike", spike):
                wd.report(
                    "grad_spike", step, value=gn,
                    message=f"grad norm {gn:.4g} at step {step} exceeds "
                            f"{factor:g}x the {ewma:.4g} EWMA")
            if not spike:
                with self._lock:
                    self._ewma_g = gn if self._ewma_g is None else \
                        (1 - _ALPHA) * self._ewma_g + _ALPHA * gn
                    self._n_g += 1
                    ewma = self._ewma_g
                self._g_ewma.set(ewma)
        # update/weight ratio out-of-band vs its own EWMA
        if math.isfinite(ratio):
            with self._lock:
                ewma_r, n_r = self._ewma_r, self._n_r
            oob = (ewma_r is not None and n_r >= _MIN_SAMPLES
                   and ratio > factor * max(ewma_r, _EPS))
            if self._transition("update_ratio", oob):
                wd.report(
                    "update_ratio", step, value=ratio,
                    message=f"update/weight ratio {ratio:.4g} at step "
                            f"{step} is out of band (> {factor:g}x the "
                            f"{ewma_r:.4g} EWMA)")
            if not oob:
                with self._lock:
                    self._ewma_r = ratio if self._ewma_r is None else \
                        (1 - _ALPHA) * self._ewma_r + _ALPHA * ratio
                    self._n_r += 1
        # bf16 master-vs-weight drift (ZeRO multi-precision units)
        if "master_drift" in vals:
            tol = master_drift_tol()
            drift = vals["master_drift"]
            bad = not math.isfinite(drift) or drift > tol
            if self._transition("master_drift", bad):
                wd.report(
                    "master_drift", step, value=drift,
                    message=f"fp32 master vs low-precision weight "
                            f"drift {drift:.4g} at step {step} exceeds "
                            f"the {tol:g} tolerance")
        out = dict(vals)
        out["step"] = step
        with self._lock:
            self._last = out
        return vals

    # ---------------- eager NaN-guard channel ----------------
    def eager_nonfinite(self, op_name: str, output_index: int) -> bool:
        """One ``nonfinite_eager`` anomaly per episode, fed by the
        inspector's invoke-funnel NaN guard; a clean checked op
        (:meth:`eager_clean`) re-arms."""
        if self._transition("nonfinite_eager", True):
            _watchdog().report(
                "nonfinite_eager", None,
                message=f"MXNET_INSPECT_NAN: op {op_name!r} produced a "
                        f"non-finite value in output {output_index}")
            return True
        return False

    def eager_clean(self):
        self._transition("nonfinite_eager", False)

    # ---------------- episodes / state ----------------
    def _transition(self, kind: str, active: bool) -> bool:
        """True exactly once per inactive->active transition (the PR 7
        budget-watchdog discipline); recovery re-arms."""
        with self._lock:
            fire = bool(active) and not self._active.get(kind)
            self._active[kind] = bool(active)
        return fire

    def last(self) -> Optional[dict]:
        """The most recently retired step's host values (plus its step
        number), for bench legs and tools/diagnose.py."""
        with self._lock:
            return dict(self._last) if self._last is not None else None

    def reset(self):
        with self._lock:
            self._ewma_g = None
            self._n_g = 0
            self._ewma_r = None
            self._n_r = 0
            self._active.clear()
            self._last = None

    # ---------------- forensics ----------------
    def _run_forensics(self, step, rec: StepNumerics, vals: dict):
        """One-shot NaN-origin forensics for a fresh non-finite episode:
        re-execute the captured batch (outside the hot loop, transfers
        blessed), write the atomic ranked dump. Returns (path, op)."""
        path = op = None
        try:
            from ..analysis import guard as _tguard
            info = None
            if rec.forensic is not None:
                with _tguard.allow_transfers(
                        "numerics NaN-origin forensics"):
                    info = rec.forensic(step)
            info = info or {}
            op = info.get("offending_op")
            layers = info.get("layers")
            if not layers and vals.get("layer_grad_norm"):
                layers = [{"param": k, "grad_norm": v}
                          for k, v in vals["layer_grad_norm"].items()]
            payload = {
                "schema_version": DUMP_SCHEMA_VERSION,
                "time_unix": time.time(),
                "kind": "nonfinite_grad",
                "step": step,
                "offending_op": op,
                "grad_norm": vals["grad_norm"],
                "param_norm": vals["param_norm"],
                "update_ratio": vals["update_ratio"],
                "nonfinite": vals["nonfinite"],
                "loss": info.get("loss"),
                "layers": layers or [],
                "context": rec.context,
                "hints": _divergence_hints(vals, rec.context),
            }
            if "reexec_error" in info:
                payload["reexec_error"] = info["reexec_error"]
            path = write_dump(payload)
            if path:
                self._c_dumps.inc()
        except Exception:        # pragma: no cover - defensive
            _LOG.warning("numerics forensics failed", exc_info=True)
        return path, op


def _finite_or_inf(v: float) -> float:
    """Sort key: non-finite norms rank first (they ARE the story)."""
    return v if math.isfinite(v) else float("inf")


_monitor = NumericsMonitor()


def monitor() -> NumericsMonitor:
    """The process-global numerics monitor
    (``mx.telemetry.numerics.monitor()``)."""
    return _monitor
