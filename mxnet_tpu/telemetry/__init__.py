"""mx.telemetry — unified runtime telemetry (docs/OBSERVABILITY.md).

Three cooperating pieces, replacing the scattered ad-hoc stats
(``guard.sync_counts``, ``engine_stats()``, ``compile_cache_stats()``,
hand-rolled bench plumbing) with one subsystem:

1. **Step-timeline tracing** (:mod:`.timeline`): structured spans for a
   train step's full lifecycle — batch fetch, prefetch h2d wait, host
   dispatch, window residency, retire — recorded from instrumentation
   points inside ``engine.DispatchWindow``, ``gluon.data
   .DevicePrefetcher``, ``gluon.TrainLoop``, and
   ``checkpoint.TrainCheckpointManager``, and emitted into the SAME
   Chrome-trace stream as the profiler's per-op events.
2. **Process-global metrics registry** (:mod:`.registry`): counters /
   gauges / histograms with bounded cardinality, named exclusively from
   the catalog in :mod:`.names`, behind pluggable exporters
   (:mod:`.exporters`): JSON :func:`snapshot`, Prometheus text file,
   periodic structured-log heartbeat.
3. **MFU gauge + anomaly watchdog** (:mod:`.watchdog`): per-bucket
   FLOPs from XLA ``cost_analysis()`` over measured step time, plus
   NaN/inf-loss and step-time-stall detection piggybacked on window
   retires.

Two further domains build on these: device memory (:mod:`.memory` —
HBM accounting, buffer census, OOM forensics) and training numerics
(:mod:`.numerics` — in-program grad/param health threaded through the
compiled step, divergence watchdog, NaN-origin forensics).

Cost model: registry counters/gauges are ALWAYS on (one uncontended
lock + float update per event, no host syncs — the transfer guard is
the enforcement mechanism). Span recording and the watchdog are gated
by :func:`enabled` — ``MXNET_TELEMETRY=1`` or :func:`enable` — and the
watchdog's NaN check adds one small device->host read per retire,
inside the already-blessed retire sync.
"""
from __future__ import annotations

import os
from typing import Optional

from . import names
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       default as registry)
from .timeline import PHASES, StepTimeline, timeline
from .watchdog import Watchdog, stall_factor, watchdog
from . import memory
from .memory import BufferCensus, MemoryReport, census
from . import numerics
from .numerics import NumericsMonitor, StepNumerics
from .exporters import (SCHEMA_VERSION, Heartbeat, heartbeat_interval,
                        prometheus_file, prometheus_text, snapshot,
                        start_heartbeat, stop_heartbeat,
                        write_prometheus)

__all__ = ["names", "registry", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "timeline", "StepTimeline", "PHASES",
           "watchdog", "Watchdog", "stall_factor", "snapshot",
           "prometheus_text", "write_prometheus", "prometheus_file",
           "Heartbeat", "start_heartbeat", "stop_heartbeat",
           "heartbeat_interval", "SCHEMA_VERSION", "enabled", "enable",
           "value", "reset", "memory", "census", "BufferCensus",
           "MemoryReport", "numerics", "NumericsMonitor",
           "StepNumerics"]

# every catalog series exists from import time: an exporter always shows
# the full schema (zero is information; absence is a question)
registry().ensure_catalog()

_OVERRIDE: Optional[bool] = None


def enabled() -> bool:
    """Whether the gated (span/watchdog) half of telemetry is on:
    ``MXNET_TELEMETRY`` truthy, or an :func:`enable` override. The
    always-on registry counters do not consult this."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    v = os.environ.get("MXNET_TELEMETRY", "").strip().lower()
    return v not in ("", "0", "off", "false", "no")


def enable(on: bool = True):
    """Programmatic override of ``MXNET_TELEMETRY`` (``enable(None)``
    restores env control)."""
    global _OVERRIDE
    _OVERRIDE = on


def active() -> bool:
    """Span-recording gate for instrumentation points: telemetry is
    enabled OR the host profiler is running (so a profiler session gets
    step spans in its Chrome trace without MXNET_TELEMETRY)."""
    if enabled():
        return True
    from ..profiler import Profiler
    prof = Profiler.get()
    return prof.running and not prof.paused


def value(name: str, label: Optional[str] = None):
    """Convenience read of one series from the default registry."""
    return registry().value(name, label)


def reset():
    """Zero every metric, clear the timeline ring and the watchdog state
    (registrations, cached metric objects, and collectors survive) —
    the test/bench isolation hook. The buffer census is NOT cleared:
    its weakref pools track live objects, not accumulated values, so
    zeroing would silently untrack still-live buffers registered once
    at compile time (``memory.census().clear()`` exists for tests that
    need a fresh census)."""
    registry().reset()
    timeline().clear()
    watchdog().reset()
    numerics.monitor().reset()
