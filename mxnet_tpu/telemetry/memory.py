"""Device-memory observability: HBM accounting, buffer census, OOM forensics.

PR 6 gave the runtime the TIME domain (step timeline, MFU watchdog);
this module is the SPACE domain — where every HBM byte of a training
run lives, measured, not asserted (docs/OBSERVABILITY.md "memory"):

1. **Compiled-program memory report** (:class:`MemoryReport`): XLA's
   ``compiled.memory_analysis()`` parsed into structured per-bucket
   bytes — arguments, outputs, temps, generated code, donated aliases —
   plus a peak-HBM estimate. Full-program compilation makes memory
   statically attributable per executable (arXiv:1810.09868): the ONE
   program the chip runs per step has ONE buffer assignment, so "will
   this batch size fit" is a number, not a try-and-see. Exposed as
   ``CompiledTrainStep.memory_report()``, merged into the analysis
   ``ProgramReport``, published as ``mx_hbm_*`` gauges.

2. **Live-buffer census** (:class:`BufferCensus`): weakref-based
   registration of the framework's long-lived device buffers by POOL —
   ``params`` (replicated weights), ``optimizer`` (momenta/moments/fp32
   masters; 1/N per replica under ZeRO, arXiv:2004.13336), ``checkpoint``
   (host capture copies awaiting serialization), ``prefetch`` (staged
   input batches), ``ndarray`` (user-tracked handles). Weakrefs mean
   registration is free of lifetime bugs: a buffer leaves its pool the
   moment its handle is collected. ``reconcile()`` diffs the pools
   against ``jax.live_arrays()`` and flags untracked device buffers as
   suspected leaks. Accounting is PER-REPLICA (addressable-shard bytes)
   via :func:`device_bytes` — the single helper
   ``optimizer_state_bytes()`` / ``state_bytes_per_replica`` now share,
   so the ZeRO N× state reduction is one measured number with one
   definition everywhere.

3. **Memory watchdog + budget**: per-device capacity from the backend's
   allocator stats where available (``device_memory_stats()``, with a
   documented live-array fallback on XLA:CPU); ``MXNET_MEMORY_BUDGET``
   arms a headroom check piggybacked on window retires that emits
   exactly ONE ``memory_budget`` anomaly per over-budget episode
   through the PR 6 watchdog channel.

4. **OOM forensics**: ``RESOURCE_EXHAUSTED`` caught at the compile and
   dispatch seams (fused step, window retire, prefetch staging, NDArray
   sync) writes one atomic ranked post-mortem JSON to
   ``MXNET_MEMORY_DUMP_DIR`` — top live buffers by pool/shape/dtype,
   per-bucket compiled peaks, window/ZeRO/batch sizing hints — and
   emits exactly one ``oom`` anomaly per failure, however many seams
   the exception propagates through (the exception object is marked).

Cost model: registration is a weakref-set add (hot paths register a
handle once); byte accounting walks the pools only when read (pull-model
registry collector, budget check at retire, dumps). Nothing here ever
adds a device->host sync — all numbers come from shapes/dtypes/shardings
and allocator counters.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import numpy as onp

import jax

from ..base import MXNetError
from . import names
from .registry import MetricsRegistry, default as _default_registry
from .watchdog import watchdog as _watchdog

__all__ = ["POOLS", "MemoryReport", "BufferCensus", "census",
           "device_bytes", "device_memory_stats", "memory_budget",
           "parse_budget", "maybe_check_budget", "dump_dir",
           "is_resource_exhausted", "maybe_record_oom", "oom_guard",
           "register_compiled_report", "compiled_reports"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

#: the census pool taxonomy (docs/OBSERVABILITY.md "memory"); earlier
#: pools win when two pools reach the same physical buffer
POOLS = ("params", "optimizer", "checkpoint", "prefetch", "kvcache",
         "ndarray")

#: schema of the OOM post-mortem dump (golden-tested)
DUMP_SCHEMA_VERSION = 1

#: buffers listed in dumps / top_buffers()
_TOP_N = 20


# ---------------------------------------------------------------------------
# byte accounting — the ONE helper every accounting path shares
# ---------------------------------------------------------------------------

def device_bytes(arr) -> int:
    """PER-REPLICA bytes of one buffer: the addressable-shard footprint
    of a ``jax.Array`` (full size for replicated arrays, 1/N for
    NamedSharding-partitioned ones — the ZeRO state buffers), ``nbytes``
    for host numpy. This is the single accounting rule behind the
    census, ``CompiledTrainStep.optimizer_state_bytes()`` and
    ``_ZeroShardPlan.state_bytes_per_replica()``."""
    d = getattr(arr, "_data", arr)          # NDArray -> jax.Array
    if d is None:
        return 0
    if isinstance(d, (onp.ndarray, onp.generic)):
        return int(d.nbytes)
    dtype = getattr(d, "dtype", None)
    if dtype is None:
        return 0
    itemsize = onp.dtype(str(dtype)).itemsize if str(dtype) == "bfloat16" \
        else dtype.itemsize
    sh = getattr(d, "sharding", None)
    if sh is not None:
        try:
            shp = sh.shard_shape(d.shape)
            return int(onp.prod(shp)) * itemsize if shp else itemsize
        except Exception:       # pragma: no cover - exotic shardings
            pass
    size = getattr(d, "size", None)
    return int(size) * itemsize if size is not None else 0


def _is_sharded(d) -> bool:
    sh = getattr(d, "sharding", None)
    if sh is None:
        return False
    try:
        return tuple(sh.shard_shape(d.shape)) != tuple(d.shape)
    except Exception:           # pragma: no cover - exotic shardings
        return False


# ---------------------------------------------------------------------------
# compiled-program memory report
# ---------------------------------------------------------------------------

class MemoryReport:
    """Structured view of one compiled executable's
    ``memory_analysis()`` (XLA's static buffer assignment):

    - ``argument_bytes`` / ``output_bytes`` — program I/O buffers;
    - ``temp_bytes`` — XLA-allocated intermediates (the activations /
      workspace the batch size drives);
    - ``generated_code_bytes`` — the executable itself in HBM;
    - ``donated_bytes`` — argument bytes aliased into outputs
      (donation working: these are NOT paid twice);
    - ``peak_bytes`` — the headroom estimate:
      ``argument + output + temp + generated_code - donated``.
    """

    FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
              "generated_code_bytes", "donated_bytes")

    def __init__(self, argument_bytes: int = 0, output_bytes: int = 0,
                 temp_bytes: int = 0, generated_code_bytes: int = 0,
                 donated_bytes: int = 0):
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.generated_code_bytes = int(generated_code_bytes)
        self.donated_bytes = int(donated_bytes)

    @property
    def peak_bytes(self) -> int:
        return max(0, self.argument_bytes + self.output_bytes
                   + self.temp_bytes + self.generated_code_bytes
                   - self.donated_bytes)

    @classmethod
    def from_compiled(cls, compiled) -> "MemoryReport":
        """Parse ``compiled.memory_analysis()`` (a
        ``CompiledMemoryStats``; lists of per-device stats take the
        first entry — SPMD programs share one buffer assignment)."""
        mem = compiled.memory_analysis()
        mem = mem[0] if isinstance(mem, (list, tuple)) else mem
        get = lambda k: int(getattr(mem, k, 0) or 0)     # noqa: E731
        return cls(argument_bytes=get("argument_size_in_bytes"),
                   output_bytes=get("output_size_in_bytes"),
                   temp_bytes=get("temp_size_in_bytes"),
                   generated_code_bytes=get("generated_code_size_in_bytes"),
                   donated_bytes=get("alias_size_in_bytes"))

    @classmethod
    def merge(cls, reports: List["MemoryReport"]) -> "MemoryReport":
        """Field-wise max over shape buckets: buckets run one at a time,
        so the headroom a mixed-shape run needs is the worst bucket's."""
        out = cls()
        for r in reports:
            for f in cls.FIELDS:
                setattr(out, f, max(getattr(out, f), getattr(r, f)))
        return out

    def to_dict(self) -> dict:
        d = {f: getattr(self, f) for f in self.FIELDS}
        d["peak_bytes"] = self.peak_bytes
        return d

    def __repr__(self):
        return (f"MemoryReport(peak={self.peak_bytes}, "
                f"args={self.argument_bytes}, temp={self.temp_bytes}, "
                f"donated={self.donated_bytes})")


#: tag -> MemoryReport dict of recently compiled programs (bounded), so
#: an OOM dump can name every bucket's static peak
_compiled_reports: "Dict[str, dict]" = {}
# bare on purpose: telemetry substrate: the audit's metrics path runs under it
_compiled_lock = threading.Lock()  # mx-lint: allow=MXA009
_COMPILED_CAP = 32


def register_compiled_report(tag: str, report: "MemoryReport"):
    """Record one compiled program's memory report for OOM forensics
    (``CompiledTrainStep.memory_report`` calls this per shape bucket)."""
    with _compiled_lock:
        if tag in _compiled_reports:
            _compiled_reports.pop(tag)
        elif len(_compiled_reports) >= _COMPILED_CAP:
            _compiled_reports.pop(next(iter(_compiled_reports)))
        _compiled_reports[tag] = report.to_dict()


def compiled_reports() -> Dict[str, dict]:
    with _compiled_lock:
        return dict(_compiled_reports)


# ---------------------------------------------------------------------------
# live-buffer census
# ---------------------------------------------------------------------------

def _leaf_arrays(handle):
    """The raw buffers one registered handle owns: NDArray -> its
    jax.Array; a checkpoint ``TrainState`` -> its host numpy arrays;
    raw jax/numpy arrays pass through."""
    arrays = getattr(handle, "arrays", None)
    if isinstance(arrays, dict):                 # checkpoint.TrainState
        return list(arrays.values())
    d = getattr(handle, "_data", handle)         # NDArray or raw array
    return [] if d is None else [d]


def _buffer_info(d, pool: str) -> dict:
    return {"pool": pool,
            "shape": list(getattr(d, "shape", ()) or ()),
            "dtype": str(getattr(d, "dtype", "?")),
            "bytes": device_bytes(d),
            "sharded": _is_sharded(d),
            "host": isinstance(d, (onp.ndarray, onp.generic))}


class BufferCensus:
    """Pool-tagged weakref registry of the framework's live buffers.

    ``register(pool, handle)`` files a weak reference to a HANDLE — an
    ``NDArray`` (whose ``_data`` rebinds per step while the handle
    survives, so one registration covers a donated buffer's whole
    lifetime), a raw ``jax.Array``, or a checkpoint ``TrainState``.
    Reads (:meth:`live_bytes_by_pool`, :meth:`buffers`,
    :meth:`reconcile`) walk the surviving weakrefs and price each
    underlying buffer once — a buffer reachable from two pools counts
    toward the earlier pool in :data:`POOLS`.
    """

    def __init__(self):
        # bare on purpose: telemetry substrate: the audit's metrics path runs under it
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        # id-keyed (NOT WeakSet: set membership would hash/== the
        # referents, and NDArray's elementwise __eq__ makes that raise)
        self._pools: Dict[str, "weakref.WeakValueDictionary"] = {
            p: weakref.WeakValueDictionary() for p in POOLS}

    def register(self, pool: str, handle) -> bool:
        """File ``handle`` under ``pool``; idempotent; returns False for
        handles that cannot be weak-referenced (plain tuples etc.)."""
        if pool not in self._pools:
            raise MXNetError(
                f"unknown census pool {pool!r}; the taxonomy is {POOLS} "
                "(docs/OBSERVABILITY.md)")
        try:
            with self._lock:
                self._pools[pool][id(handle)] = handle
            return True
        except TypeError:
            return False

    def clear(self):
        """Drop every registration (test isolation; live handles are
        NOT re-registered — their owners re-file on next accounting)."""
        with self._lock:
            for s in self._pools.values():
                s.clear()

    # ---------------- accounting ----------------
    def _collect(self) -> Dict[str, Dict[int, dict]]:
        """pool -> {id(buffer): info}, deduped across pools by POOLS
        precedence (a buffer never counts twice)."""
        with self._lock:
            handles = {p: list(s.values()) for p, s in self._pools.items()}
        seen: set = set()
        out: Dict[str, Dict[int, dict]] = {}
        for pool in POOLS:
            bufs: Dict[int, dict] = {}
            for h in handles[pool]:
                for d in _leaf_arrays(h):
                    k = id(d)
                    if k in seen:
                        continue
                    seen.add(k)
                    bufs[k] = _buffer_info(d, pool)
            out[pool] = bufs
        return out

    def live_bytes_by_pool(self) -> Dict[str, int]:
        """Current per-replica bytes per pool (every pool present, 0
        when empty) — the measured form of the ZeRO paper's state-memory
        claim: compare ``optimizer`` here between the plain and sharded
        modes."""
        c = self._collect()
        return {p: sum(i["bytes"] for i in c[p].values()) for p in POOLS}

    def live_count_by_pool(self) -> Dict[str, int]:
        c = self._collect()
        return {p: len(c[p]) for p in POOLS}

    def buffers(self, pool: Optional[str] = None) -> List[dict]:
        """Live buffer infos (``{pool, shape, dtype, bytes, sharded,
        host}``), biggest first."""
        c = self._collect()
        pools = (pool,) if pool is not None else POOLS
        out = [i for p in pools for i in c.get(p, {}).values()]
        return sorted(out, key=lambda i: -i["bytes"])

    def top_buffers(self, n: int = _TOP_N) -> List[dict]:
        return self.buffers()[:n]

    # ---------------- reconciliation ----------------
    def reconcile(self) -> dict:
        """Diff the pools against ``jax.live_arrays()``: device buffers
        alive in the process but claimed by NO pool are suspected leaks
        (or untracked user arrays). Host (numpy) pool entries are
        outside jax's view and excluded from the diff."""
        c = self._collect()
        tracked_ids = {k for bufs in c.values() for k in bufs}
        untracked = []
        total = 0
        try:
            live = jax.live_arrays()
        except Exception:       # pragma: no cover - defensive
            live = []
        for a in live:
            if id(a) in tracked_ids:
                continue
            info = _buffer_info(a, "untracked")
            untracked.append(info)
            total += info["bytes"]
        untracked.sort(key=lambda i: -i["bytes"])
        return {
            "by_pool": {p: sum(i["bytes"] for i in c[p].values())
                        for p in POOLS},
            "counts": {p: len(c[p]) for p in POOLS},
            "untracked": {"count": len(untracked), "bytes": total,
                          "top": untracked[:_TOP_N]},
        }

    # ---------------- registry publication ----------------
    def publish(self, registry: Optional[MetricsRegistry] = None):
        """Refresh the ``mx_mem_pool_*`` / ``mx_mem_untracked_bytes``
        gauges from the current census (the pull-model collector
        exporters run before every export)."""
        reg = registry if registry is not None else _default_registry()
        rec = self.reconcile()
        g_bytes = reg.gauge(names.MEM_POOL_BYTES)
        g_count = reg.gauge(names.MEM_POOL_BUFFERS)
        for p in POOLS:
            g_bytes.set(rec["by_pool"][p], label=p)
            g_count.set(rec["counts"][p], label=p)
        reg.gauge(names.MEM_UNTRACKED_BYTES).set(
            rec["untracked"]["bytes"])


_census = BufferCensus()


def census() -> BufferCensus:
    """The process-global buffer census (``mx.telemetry.memory.census()``)."""
    return _census


def _collector(reg: MetricsRegistry):
    """Registry pull-model collector: census pools + device stats are
    refreshed before every snapshot/Prometheus export."""
    _census.publish(reg)
    device_memory_stats(registry=reg)
    b = memory_budget()
    if b is not None:
        reg.gauge(names.MEM_BUDGET_BYTES).set(b)


# ---------------------------------------------------------------------------
# device capacity + budget watchdog
# ---------------------------------------------------------------------------

def device_memory_stats(registry: Optional[MetricsRegistry] = None
                        ) -> Dict[str, dict]:
    """Per-device memory stats, routed through the telemetry catalog
    (``mx_mem_device_*`` gauges): allocator counters
    (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``,
    ``source: "allocator"``) where the backend exposes them (TPU/GPU
    BFC). XLA:CPU exposes NO allocator stats — instead of the silent
    ``None``s the old profiler dict carried, the documented fallback
    prices every ``jax.live_arrays()`` shard on its device
    (``source: "live_arrays"``; ``peak_bytes_in_use``/``bytes_limit``
    stay None — live accounting has no high-water mark)."""
    reg = registry if registry is not None else _default_registry()
    out: Dict[str, dict] = {}
    fallback_devices = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d)] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
                "source": "allocator",
            }
        else:
            fallback_devices.append(str(d))
    if fallback_devices:
        per_dev: Dict[str, int] = {k: 0 for k in fallback_devices}
        try:
            for a in jax.live_arrays():
                for shard in getattr(a, "addressable_shards", []):
                    k = str(shard.device)
                    if k in per_dev:
                        per_dev[k] += device_bytes(shard.data)
        except Exception:       # pragma: no cover - defensive
            pass
        for k in fallback_devices:
            out[k] = {"bytes_in_use": per_dev.get(k, 0),
                      "peak_bytes_in_use": None, "bytes_limit": None,
                      "source": "live_arrays"}
    g_use = reg.gauge(names.MEM_DEVICE_IN_USE)
    g_peak = reg.gauge(names.MEM_DEVICE_PEAK)
    g_lim = reg.gauge(names.MEM_DEVICE_LIMIT)
    for k, s in out.items():
        g_use.set(s["bytes_in_use"] or 0, label=k)
        g_peak.set(-1 if s["peak_bytes_in_use"] is None
                   else s["peak_bytes_in_use"], label=k)
        g_lim.set(-1 if s["bytes_limit"] is None else s["bytes_limit"],
                  label=k)
    return out


_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_budget(value: str,
                 capacity: Optional[int] = None) -> Optional[int]:
    """Parse a ``MXNET_MEMORY_BUDGET`` value: plain bytes (``8589934592``),
    a K/M/G/T-suffixed size (``28g``, ``500MB``), or a strict fraction
    in (0, 1) of the device capacity (``0.9`` — only meaningful when
    the backend reports ``bytes_limit``). Returns None for
    unset/unparsable."""
    v = (value or "").strip().lower()
    if not v:
        return None
    mult = 1
    if v.endswith("b"):
        v = v[:-1]
    if v and v[-1] in _SUFFIX:
        mult = _SUFFIX[v[-1]]
        v = v[:-1]
    try:
        f = float(v)
    except ValueError:
        return None
    if f <= 0:
        return None
    if mult == 1 and f < 1.0:
        return int(f * capacity) if capacity else None
    return int(f * mult)


def _device_capacity() -> Optional[int]:
    cap = None
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        lim = (stats or {}).get("bytes_limit")
        if lim:
            cap = lim if cap is None else min(cap, lim)
    return cap


def memory_budget() -> Optional[int]:
    """The configured headroom bound in bytes (``MXNET_MEMORY_BUDGET``),
    or None when unset."""
    raw = os.environ.get("MXNET_MEMORY_BUDGET")
    if not raw:
        return None
    return parse_budget(raw, capacity=_device_capacity())


def maybe_check_budget(step=None) -> Optional[dict]:
    """The retire-piggybacked headroom check (engine.DispatchWindow
    feeds this when telemetry is enabled): no-op when
    ``MXNET_MEMORY_BUDGET`` is unset. In-use bytes come from the
    allocator's worst device where stats exist, else the census pools
    (tracked buffers only — the cheap hot-path number). Exceeding the
    budget emits exactly one ``memory_budget`` anomaly per episode via
    the watchdog channel; dropping back under re-arms."""
    budget = memory_budget()
    if budget is None:
        return None
    in_use = None
    source = "allocator"
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        b = (stats or {}).get("bytes_in_use")
        if b is not None:
            in_use = b if in_use is None else max(in_use, b)
    if in_use is None:
        by_pool = _census.live_bytes_by_pool()
        in_use = sum(by_pool.values())
        source = "census"
    over = in_use > budget
    reg = _default_registry()
    reg.gauge(names.MEM_BUDGET_BYTES).set(budget)
    top = ""
    if over:
        by_pool = _census.live_bytes_by_pool()
        if any(by_pool.values()):
            pool = max(by_pool, key=by_pool.get)
            top = f"; largest pool: {pool} ({by_pool[pool]} B)"
    _watchdog().episode(
        "memory_budget", over, step=step, value=in_use,
        message=(f"device memory {in_use} B exceeds the "
                 f"MXNET_MEMORY_BUDGET of {budget} B "
                 f"({source} accounting){top}") if over else "")
    return {"budget": budget, "in_use": in_use, "over": over,
            "source": source}


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def dump_dir() -> Optional[str]:
    """``MXNET_MEMORY_DUMP_DIR`` (None = no post-mortem files; the
    ``oom`` anomaly event still fires)."""
    return os.environ.get("MXNET_MEMORY_DUMP_DIR") or None


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating", "Resource exhausted")


def _exc_chain(exc):
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        yield exc
        exc = exc.__cause__ or exc.__context__


def is_resource_exhausted(exc: BaseException) -> bool:
    """Whether ``exc`` (or anything in its cause chain) is an XLA
    allocation failure."""
    for e in _exc_chain(exc):
        if type(e).__name__ == "XlaRuntimeError" and "RESOURCE" in str(e):
            return True
        msg = str(e)
        if any(m in msg for m in _OOM_MARKERS):
            return True
    return False


def _sizing_hints(by_pool: Dict[str, int], compiled: Dict[str, dict],
                  budget: Optional[int]) -> List[str]:
    """Actionable knobs ranked by what the census says dominates."""
    hints = []
    opt, params = by_pool.get("optimizer", 0), by_pool.get("params", 0)
    sharded_opt = any(i["sharded"]
                      for i in _census.buffers("optimizer"))
    if opt and not sharded_opt and opt >= params / 2:
        hints.append(
            "optimizer state is fully replicated: enable the ZeRO-1 "
            "sharded update (compile_step on a dp mesh, zero_shard=True) "
            "for an ~N-per-replica reduction (docs/PERF_NOTES.md)")
    if by_pool.get("prefetch", 0):
        hints.append(
            "staged input batches hold HBM: lower MXNET_DEVICE_PREFETCH "
            "and/or MXNET_INFLIGHT_STEPS to shrink the in-flight window")
    peak = max((r.get("peak_bytes", 0) for r in compiled.values()),
               default=0)
    temp = max((r.get("temp_bytes", 0) for r in compiled.values()),
               default=0)
    if temp and temp >= peak / 2:
        hints.append(
            "XLA temp buffers (activations/workspace) dominate the "
            "compiled peak: reduce the batch size or enable remat "
            "(hybridize(backend='remat'))")
    if by_pool.get("checkpoint", 0):
        hints.append(
            "a checkpoint capture is in flight: stagger checkpoint_every "
            "away from peak-memory steps, or save with block=True")
    if budget is not None:
        hints.append(
            f"MXNET_MEMORY_BUDGET is {budget} B: re-run with "
            "tools/diagnose.py --memory to see standing headroom")
    if not hints:
        hints.append(
            "inspect top_buffers below; XLA_PYTHON_CLIENT_MEM_FRACTION "
            "bounds the allocator if the host shares the device")
    return hints


def maybe_record_oom(exc: BaseException, seam: str,
                     step=None) -> Optional[str]:
    """OOM post-mortem: if ``exc`` is an allocation failure not already
    handled at an inner seam, emit exactly one ``oom`` anomaly and write
    one ranked dump file (atomic tmp+rename) to
    ``MXNET_MEMORY_DUMP_DIR``. Returns the dump path (None when no dump
    was written). Never raises — forensics must not mask the original
    error."""
    try:
        if not is_resource_exhausted(exc):
            return None
        for e in _exc_chain(exc):
            if getattr(e, "_mx_oom_handled", False):
                return None
        try:
            exc._mx_oom_handled = True
        except Exception:        # pragma: no cover - frozen exc types
            pass
        rec = _census.reconcile()
        by_pool = rec["by_pool"]
        compiled = compiled_reports()
        budget = memory_budget()
        dump = {
            "schema_version": DUMP_SCHEMA_VERSION,
            "time_unix": time.time(),
            "seam": seam,
            "step": step,
            "error": f"{type(exc).__name__}: {exc}",
            "budget_bytes": budget,
            "device_stats": device_memory_stats(),
            "live_bytes_by_pool": by_pool,
            "untracked": rec["untracked"],
            "top_buffers": _census.top_buffers(_TOP_N),
            "compiled": compiled,
            "hints": _sizing_hints(by_pool, compiled, budget),
        }
        path = None
        d = dump_dir()
        if d:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"mx_oom_{int(time.time())}_{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dump, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _default_registry().counter(names.OOM_DUMPS).inc()
        _watchdog().report(
            "oom", step, value=None,
            message=f"allocation failure at {seam}"
                    + (f" (step {step})" if step is not None else "")
                    + (f"; post-mortem dump: {path}" if path else
                       "; set MXNET_MEMORY_DUMP_DIR for a ranked "
                       "post-mortem dump"))
        return path
    except Exception:            # pragma: no cover - defensive
        _LOG.warning("OOM forensics failed", exc_info=True)
        return None


@contextmanager
def oom_guard(seam: str, step=None):
    """Wrap a compile/dispatch seam: an escaping allocation failure gets
    its post-mortem recorded (once, however nested the seams) and then
    propagates unchanged."""
    try:
        yield
    except BaseException as e:
        maybe_record_oom(e, seam, step=step)
        raise


# publish pools/device stats before every export (snapshot, Prometheus)
_default_registry().register_collector(_collector)
