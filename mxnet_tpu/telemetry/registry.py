"""Process-global metrics registry: counters, gauges, histograms.

The always-on half of the telemetry subsystem (docs/OBSERVABILITY.md).
Hot-path cost is one uncontended lock + a dict/float update per event —
no host syncs, no allocation beyond first registration — so the
instrumentation points (window retires, prefetch waits, guard sync
census) feed it unconditionally; the heavier span/watchdog machinery is
gated behind ``MXNET_TELEMETRY`` instead.

Cardinality is bounded by construction: a metric has at most ONE label
key, fixed at registration, and at most ``names.MAX_LABEL_VALUES``
distinct values — further values collapse into ``names.OVERFLOW_LABEL``,
so a mistake upstream (per-step or per-shape label values) degrades an
exporter to one extra series, never an unbounded one.

Registration funnels through :func:`names.check`: framework (``mx_``)
names must come from the catalog in ``telemetry/names.py``, which the
tier-1 metric-name lint sweep keeps as the single source of truth.
"""
from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..base import MXNetError
from . import names

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "default",
           "DEFAULT_BUCKETS"]

#: default histogram bucket upper bounds, seconds (phase/step/checkpoint
#: latencies from ~0.1ms to tens of seconds)
DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                   1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0)

_UNLABELED = ""


class _Metric:
    kind = "abstract"

    def __init__(self, name: str, help: str = "",
                 label_key: Optional[str] = None):
        self.name = name
        self.help = help
        self.label_key = label_key
        # bare on purpose: telemetry substrate: auditing the metrics lock would recurse
        self._lock = threading.Lock()  # mx-lint: allow=MXA009

    def _slot(self, label: Optional[str]) -> str:
        """Normalize + bound the label value (call under self._lock)."""
        if label is None:
            if self.label_key is not None:
                raise MXNetError(
                    f"metric {self.name!r} requires a "
                    f"{self.label_key!r} label value")
            return _UNLABELED
        if self.label_key is None:
            raise MXNetError(
                f"metric {self.name!r} was registered without a label "
                f"key; got label {label!r}")
        label = str(label)
        if label not in self._values and \
                len(self._values) >= names.MAX_LABEL_VALUES:
            return names.OVERFLOW_LABEL
        return label

    def values(self) -> dict:
        """label value -> current value ('' for unlabeled)."""
        with self._lock:
            return dict(self._values)


class Counter(_Metric):
    """Monotonic float counter (optionally labeled)."""

    kind = "counter"

    def __init__(self, name, help="", label_key=None):
        super().__init__(name, help, label_key)
        self._values: Dict[str, float] = {}

    def inc(self, v: float = 1.0, label: Optional[str] = None):
        if v < 0:
            raise MXNetError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            slot = self._slot(label)
            self._values[slot] = self._values.get(slot, 0.0) + v

    def value(self, label: Optional[str] = None) -> float:
        with self._lock:
            return self._values.get(
                _UNLABELED if label is None else str(label), 0.0)

    def _reset(self):
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    """Point-in-time value (optionally labeled)."""

    kind = "gauge"

    def __init__(self, name, help="", label_key=None):
        super().__init__(name, help, label_key)
        self._values: Dict[str, float] = {}

    def set(self, v: float, label: Optional[str] = None):
        with self._lock:
            self._values[self._slot(label)] = float(v)

    def add(self, v: float, label: Optional[str] = None):
        with self._lock:
            slot = self._slot(label)
            self._values[slot] = self._values.get(slot, 0.0) + v

    def value(self, label: Optional[str] = None) -> Optional[float]:
        with self._lock:
            return self._values.get(
                _UNLABELED if label is None else str(label))

    def _reset(self):
        with self._lock:
            self._values.clear()


class _HistSlot:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets):
        self.counts = [0] * (n_buckets + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram with quantile estimation.

    Buckets are cumulative-style at export (Prometheus ``le``);
    internally per-bucket counts. ``percentile`` interpolates linearly
    inside the winning bucket — exact enough for p50/p99 phase summaries
    (the raw-event path in timeline.py is exact for recent steps).
    """

    kind = "histogram"

    def __init__(self, name, help="", label_key=None, buckets=None):
        super().__init__(name, help, label_key)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not self.buckets:
            raise MXNetError(f"histogram {name!r} needs >= 1 bucket")
        self._values: Dict[str, _HistSlot] = {}

    def observe(self, v: float, label: Optional[str] = None):
        v = float(v)
        with self._lock:
            slot = self._slot(label)
            h = self._values.get(slot)
            if h is None:
                h = self._values[slot] = _HistSlot(len(self.buckets))
            h.counts[bisect.bisect_left(self.buckets, v)] += 1
            h.sum += v
            h.count += 1

    def _get(self, label) -> Optional[_HistSlot]:
        return self._values.get(
            _UNLABELED if label is None else str(label))

    def count(self, label: Optional[str] = None) -> int:
        with self._lock:
            h = self._get(label)
            return h.count if h else 0

    def sum(self, label: Optional[str] = None) -> float:
        with self._lock:
            h = self._get(label)
            return h.sum if h else 0.0

    def percentile(self, p: float, label: Optional[str] = None
                   ) -> Optional[float]:
        """Estimate the p-th percentile (0..100) from bucket counts."""
        with self._lock:
            h = self._get(label)
            if h is None or h.count == 0:
                return None
            rank = p / 100.0 * h.count
            seen = 0.0
            lo = 0.0
            for i, c in enumerate(h.counts):
                if c == 0:
                    if i < len(self.buckets):
                        lo = self.buckets[i]
                    continue
                if seen + c >= rank:
                    hi = self.buckets[i] if i < len(self.buckets) \
                        else self.buckets[-1]
                    frac = (rank - seen) / c
                    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                seen += c
                if i < len(self.buckets):
                    lo = self.buckets[i]
            return self.buckets[-1]   # pragma: no cover - numeric edge

    def snapshot_slot(self, label: Optional[str] = None) -> Optional[dict]:
        """{count, sum, p50, p99, buckets:{le->cumulative}} for export."""
        with self._lock:
            h = self._get(label)
            if h is None:
                return None
        out = {"count": h.count, "sum": h.sum,
               "p50": self.percentile(50, label),
               "p99": self.percentile(99, label)}
        cum, buckets = 0, {}
        for le, c in zip(self.buckets, h.counts):
            cum += c
            buckets[repr(le)] = cum
        buckets["+Inf"] = h.count
        out["buckets"] = buckets
        return out

    def labels(self) -> List[str]:
        with self._lock:
            return sorted(self._values)

    def _reset(self):
        with self._lock:
            self._values.clear()


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name -> metric map with get-or-create registration and pull-model
    collectors (callables refreshed before each export)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []
        # bare on purpose: telemetry substrate: auditing the registry would recurse
        self._lock = threading.Lock()  # mx-lint: allow=MXA009

    # ---------------- registration ----------------
    def _register(self, kind: str, name: str, help: str,
                  label_key: Optional[str], **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise MXNetError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {kind}")
                if label_key is not None and m.label_key != label_key:
                    raise MXNetError(
                        f"metric {name!r} already registered with label "
                        f"key {m.label_key!r}, not {label_key!r}")
                return m
            names.check(name, kind)
            if name.startswith("mx_"):
                decl = names.CATALOG[name]
                help = help or decl["help"]
                if label_key is None:
                    label_key = decl["label"]
                elif decl["label"] != label_key:
                    raise MXNetError(
                        f"metric {name!r} declared with label "
                        f"{decl['label']!r} in the catalog, "
                        f"got {label_key!r}")
            m = _KINDS[kind](name, help=help, label_key=label_key,
                             **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                label_key: Optional[str] = None) -> Counter:
        return self._register("counter", name, help, label_key)

    def gauge(self, name: str, help: str = "",
              label_key: Optional[str] = None) -> Gauge:
        return self._register("gauge", name, help, label_key)

    def histogram(self, name: str, help: str = "",
                  label_key: Optional[str] = None,
                  buckets=None) -> Histogram:
        return self._register("histogram", name, help, label_key,
                              buckets=buckets)

    def ensure_catalog(self):
        """Pre-register every catalog series so exporters always show
        the full schema (a zero counter is information; a missing one is
        a question)."""
        for name, decl in names.CATALOG.items():
            self._register(decl["kind"], name, decl["help"], decl["label"])

    # ---------------- access ----------------
    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def value(self, name: str, label: Optional[str] = None):
        """Convenience read: counter/gauge value or histogram count."""
        m = self.get(name)
        if m is None:
            return None
        if isinstance(m, Histogram):
            return m.count(label)
        return m.value(label)

    # ---------------- collectors ----------------
    def register_collector(self, fn: Callable[["MetricsRegistry"], None]):
        """Pull-model refresh hook, run at collect()/export time (e.g.
        runtime.compile_cache_stats -> gauges)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self) -> List[_Metric]:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:       # a broken collector must not kill
                import logging      # the exporter
                logging.getLogger("mxnet_tpu.telemetry").warning(
                    "telemetry collector %r failed", fn, exc_info=True)
        return self.metrics()

    # ---------------- lifecycle ----------------
    def reset(self):
        """Zero every metric IN PLACE (call sites cache metric objects,
        so objects survive; values drop to empty/zero). Collectors and
        registrations persist."""
        for m in self.metrics():
            m._reset()


_default = MetricsRegistry()


def default() -> MetricsRegistry:
    """The process-global registry every framework instrumentation point
    feeds (``mx.telemetry.registry()``)."""
    return _default
