"""Step-timeline tracing: structured spans over a train step's lifecycle.

One pipelined train step passes through five host-observable phases —

    batch_fetch   producer pulls + stages the batch (prefetcher thread)
    h2d_wait      consumer wait on the staged device-resident batch
    dispatch      host time inside the compiled step call (enqueue)
    window        residency in the in-flight dispatch window (push->done)
    retire        the blocking wait at the window boundary (FIFO oldest)

plus ``checkpoint`` for snapshot captures. Each instrumentation point
(engine.DispatchWindow, gluon.data.DevicePrefetcher, gluon.TrainLoop,
checkpoint.TrainCheckpointManager) records its span here; the timeline

- feeds the ``mx_step_phase_seconds{phase=}`` histogram in the metrics
  registry (always),
- keeps a bounded ring of raw span events for exact p50/p99 summaries
  (tools/diagnose.py --telemetry), and
- when the host profiler is running, emits each span into the SAME
  Chrome-trace stream as the per-op events (``cat: "step"``, args
  carrying the step number and phase) — so host ops and step phases land
  on one chrome://tracing / Perfetto timeline. Device kernels align via
  the ``jax.profiler`` step annotation the TrainLoop wraps dispatch in.

Span recording is gated by :func:`active` at the call sites: on when
``MXNET_TELEMETRY`` is set (``mx.telemetry.enable()``) or when the host
profiler is running; the registry counters stay always-on regardless.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from ..base import MXNetError
from . import names
from .registry import default as _default_registry

__all__ = ["PHASES", "StepTimeline", "timeline"]

#: the span vocabulary — documented in docs/OBSERVABILITY.md; record()
#: rejects anything else so the phase label stays bounded
PHASES = ("batch_fetch", "h2d_wait", "dispatch", "window", "retire",
          "checkpoint")


class StepTimeline:
    """Bounded ring of step-phase spans + the phase-duration histogram."""

    def __init__(self, capacity: int = 2048):
        self._events: "deque[dict]" = deque(maxlen=capacity)
        # bare on purpose: telemetry substrate: the audit's metrics path runs under it
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        self._hist = _default_registry().histogram(
            names.STEP_PHASE_SECONDS, label_key="phase")

    # ---------------- recording ----------------
    def record(self, phase: str, t0: float, t1: float,
               step: Optional[int] = None):
        """Record one span: ``t0``/``t1`` are ``time.perf_counter()``
        stamps; ``step`` is the global step number where the
        instrumentation point knows it (prefetcher spans use their own
        batch ordinal). Also mirrors the span into the profiler's
        Chrome-trace stream when it is running."""
        if phase not in PHASES:
            raise MXNetError(
                f"unknown step phase {phase!r}; the span vocabulary is "
                f"{PHASES} (docs/OBSERVABILITY.md)")
        dur = max(0.0, t1 - t0)
        self._hist.observe(dur, label=phase)
        with self._lock:
            self._events.append(
                {"phase": phase, "step": step, "t0": t0, "t1": t1,
                 "dur": dur})
        self._emit_trace(phase, t0, t1, step)

    @staticmethod
    def _emit_trace(phase, t0, t1, step):
        from ..profiler import Profiler
        prof = Profiler.get()
        if prof.running and not prof.paused:
            prof.record(f"step:{phase}", t0, t1, cat="step",
                        args={"step": step, "phase": phase})

    # ---------------- queries ----------------
    def events(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if n is None else evs[-n:]

    def clear(self):
        with self._lock:
            self._events.clear()

    def summary(self, last_steps: Optional[int] = None) -> Dict[str, dict]:
        """Exact per-phase stats over the retained ring (optionally the
        spans of the last N distinct step numbers): count, total/p50/p99
        milliseconds — what ``tools/diagnose.py --telemetry`` prints."""
        evs = self.events()
        if last_steps is not None:
            steps = sorted({e["step"] for e in evs
                            if e["step"] is not None})
            keep = set(steps[-last_steps:])
            evs = [e for e in evs
                   if e["step"] is None or e["step"] in keep]
        by_phase: Dict[str, List[float]] = {}
        for e in evs:
            by_phase.setdefault(e["phase"], []).append(e["dur"])
        import numpy as onp
        out = {}
        for phase in PHASES:
            durs = by_phase.get(phase)
            if not durs:
                continue
            a = onp.asarray(durs)
            out[phase] = {
                "count": int(a.size),
                "total_ms": float(a.sum() * 1e3),
                "p50_ms": float(onp.percentile(a, 50) * 1e3),
                "p99_ms": float(onp.percentile(a, 99) * 1e3),
                "max_ms": float(a.max() * 1e3),
            }
        return out


_timeline = StepTimeline()


def timeline() -> StepTimeline:
    """The process-global step timeline (``mx.telemetry.timeline()``)."""
    return _timeline
