"""Metric-name catalog — the single source of truth for runtime telemetry.

Every metric the framework registers lives HERE as a module constant,
and the registry enforces it at registration time: a name must match the
convention regex, and any ``mx_``-prefixed name must be declared in
:data:`CATALOG` with the kind it is registered as.  Framework code never
passes string literals to ``registry.counter/gauge/histogram`` — it
imports the constant (the tier-1 lint sweep in
tests/test_metric_names_lint.py greps for violations), so exporter
cardinality cannot silently drift: a new series requires a catalog entry,
which requires touching this file and docs/OBSERVABILITY.md.

Naming convention (Prometheus-compatible):

- ``<prefix>_<what>[_<unit>]``, lowercase snake case, >= 2 tokens
  (:data:`NAME_RE`); the ``mx_`` prefix is RESERVED for catalog
  entries — user code registers its own metrics under its own prefix;
- counters end in ``_total``;
- histograms end in a unit suffix (``_seconds`` for latencies,
  ``_ratio`` for unitless ratios such as the numerics update/weight
  ratio);
- gauges end in neither ``_total`` nor ``_bucket`` (a unit suffix such
  as ``_seconds`` is fine);
- label keys are single, fixed per metric, with bounded value
  cardinality (:data:`MAX_LABEL_VALUES`; overflow collapses into
  :data:`OVERFLOW_LABEL`).
"""
from __future__ import annotations

import re

__all__ = ["NAME_RE", "MAX_LABEL_VALUES", "OVERFLOW_LABEL", "CATALOG",
           "is_valid", "kind_ok", "check"]

NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")

#: max distinct label values per labeled metric before new values
#: collapse into OVERFLOW_LABEL (bounded exporter cardinality)
MAX_LABEL_VALUES = 24
OVERFLOW_LABEL = "other"

# ---------------------------------------------------------------------------
# engine / dispatch window
# ---------------------------------------------------------------------------
TRAIN_STEPS = "mx_train_steps_total"
WINDOW_PUSHES = "mx_engine_window_pushes_total"
WINDOW_RETIRES = "mx_engine_window_retires_total"
WINDOW_ERRORS = "mx_engine_window_errors_total"
WINDOW_OCCUPANCY = "mx_engine_window_occupancy"
WINDOW_CAPACITY = "mx_engine_window_capacity"

# ---------------------------------------------------------------------------
# transfer guard (analysis/guard.py sync census)
# ---------------------------------------------------------------------------
HOST_SYNCS = "mx_guard_host_syncs_total"

# ---------------------------------------------------------------------------
# device input prefetch (gluon/data/prefetcher.py)
# ---------------------------------------------------------------------------
PREFETCH_BATCHES = "mx_prefetch_batches_total"
PREFETCH_STARVATION = "mx_prefetch_starvation_total"
PREFETCH_INPUT_WAIT = "mx_prefetch_input_wait_seconds_total"

# ---------------------------------------------------------------------------
# compilation (runtime.py persistent cache + fused_step retraces)
# ---------------------------------------------------------------------------
COMPILE_CACHE_HITS = "mx_compile_cache_hits_total"
COMPILE_CACHE_MISSES = "mx_compile_cache_misses_total"
COMPILE_CACHE_ENABLED = "mx_compile_cache_enabled"
COMPILE_RETRACES = "mx_compile_retraces_total"

# ---------------------------------------------------------------------------
# checkpoint (checkpoint/manager.py)
# ---------------------------------------------------------------------------
CHECKPOINT_SAVES = "mx_checkpoint_saves_total"
CHECKPOINT_ERRORS = "mx_checkpoint_errors_total"
CHECKPOINT_RESTORES = "mx_checkpoint_restores_total"
CHECKPOINT_CAPTURE_SECONDS = "mx_checkpoint_capture_seconds"
CHECKPOINT_SAVE_SECONDS = "mx_checkpoint_save_seconds"
CHECKPOINT_RECOVERY_SECONDS = "mx_checkpoint_recovery_seconds"

# ---------------------------------------------------------------------------
# elastic training supervisor (elastic/supervisor.py)
# ---------------------------------------------------------------------------
ELASTIC_RECOVERIES = "mx_elastic_recoveries_total"
ELASTIC_DOWNTIME_SECONDS = "mx_elastic_recovery_downtime_seconds"
ELASTIC_WORLD_SIZE = "mx_elastic_world_size"
ELASTIC_PREEMPTIONS = "mx_elastic_preemptions_total"

# ---------------------------------------------------------------------------
# step timeline (telemetry/timeline.py)
# ---------------------------------------------------------------------------
STEP_PHASE_SECONDS = "mx_step_phase_seconds"
STEP_TIME_SECONDS = "mx_step_time_seconds"

# ---------------------------------------------------------------------------
# MFU gauge + anomaly watchdog (telemetry/watchdog.py)
# ---------------------------------------------------------------------------
MODEL_FLOPS_PER_STEP = "mx_model_flops_per_step"
MODEL_FLOPS_PER_SEC = "mx_model_flops_per_sec"
MFU = "mx_model_mfu_ratio"
STEP_TIME_EWMA = "mx_watchdog_step_time_ewma_seconds"
ANOMALIES = "mx_anomalies_total"

# ---------------------------------------------------------------------------
# device-memory observability (telemetry/memory.py)
# ---------------------------------------------------------------------------
HBM_COMPILED_BYTES = "mx_hbm_compiled_bytes"
HBM_PEAK_BYTES = "mx_hbm_peak_estimate_bytes"
MEM_POOL_BYTES = "mx_mem_pool_bytes"
MEM_POOL_BUFFERS = "mx_mem_pool_buffers"
MEM_UNTRACKED_BYTES = "mx_mem_untracked_bytes"
MEM_DEVICE_IN_USE = "mx_mem_device_bytes_in_use"
MEM_DEVICE_PEAK = "mx_mem_device_peak_bytes"
MEM_DEVICE_LIMIT = "mx_mem_device_limit_bytes"
MEM_BUDGET_BYTES = "mx_mem_budget_bytes"
OOM_DUMPS = "mx_mem_oom_dumps_total"

# ---------------------------------------------------------------------------
# training-numerics observability (telemetry/numerics.py)
# ---------------------------------------------------------------------------
NUMERICS_GRAD_NORM = "mx_numerics_grad_norm"
NUMERICS_PARAM_NORM = "mx_numerics_param_norm"
NUMERICS_GRAD_NORM_EWMA = "mx_numerics_grad_norm_ewma"
NUMERICS_UPDATE_RATIO = "mx_numerics_update_ratio"
NUMERICS_LAYER_GRAD_NORM = "mx_numerics_layer_grad_norm"
NUMERICS_MASTER_DRIFT = "mx_numerics_master_drift"
NUMERICS_NONFINITE = "mx_numerics_nonfinite_total"
NUMERICS_DUMPS = "mx_numerics_dumps_total"

# ---------------------------------------------------------------------------
# fusion census (analysis/fusion.py)
# ---------------------------------------------------------------------------
FUSION_REGIONS = "mx_fusion_regions"
FUSION_STRANDED = "mx_fusion_stranded_ops"
FUSION_BOUNDARY_BYTES = "mx_fusion_boundary_bytes"
FUSION_COMPUTE_BOUND = "mx_fusion_compute_bound_ratio"

# ---------------------------------------------------------------------------
# SPMD sharding analysis (analysis/sharding.py)
# ---------------------------------------------------------------------------
SHARDING_RESHARDS = "mx_sharding_implicit_reshards"
SHARDING_RESHARD_BYTES = "mx_sharding_reshard_bytes"
SHARDING_COMM_COST = "mx_sharding_comm_cost_seconds"
SHARDING_COLLECTIVE_BYTES = "mx_sharding_collective_bytes"
SHARDING_EXPOSED_COMM = "mx_sharding_exposed_comm_seconds"
OVERLAP_FRACTION = "mx_overlap_fraction"

# ---------------------------------------------------------------------------
# Pallas kernel layer (ops/kernels dispatch gate)
# ---------------------------------------------------------------------------
KERNEL_DISPATCH = "mx_kernel_dispatch_total"

# ---------------------------------------------------------------------------
# self-tuning performance autopilot (tuning/)
# ---------------------------------------------------------------------------
AUTOTUNE_TRIALS = "mx_autotune_trials_total"
AUTOTUNE_CACHE_HITS = "mx_autotune_cache_hits_total"
AUTOTUNE_CACHE_MISSES = "mx_autotune_cache_misses_total"
AUTOTUNE_ACTIVE = "mx_autotune_active_config"

# ---------------------------------------------------------------------------
# inference serving engine (serving/batcher.py)
# ---------------------------------------------------------------------------
SERVING_REQUESTS = "mx_serving_requests_total"
SERVING_BATCHES = "mx_serving_batches_total"
SERVING_QUEUE_DEPTH = "mx_serving_queue_depth"
SERVING_INFLIGHT = "mx_serving_inflight_batches"
SERVING_OCCUPANCY = "mx_serving_batch_occupancy_ratio"
SERVING_LATENCY = "mx_serving_request_seconds"

# ---------------------------------------------------------------------------
# resilient serving (serving/resilience.py + batcher.py admission control)
# ---------------------------------------------------------------------------
SERVING_REJECTED = "mx_serving_rejected_total"
SERVING_DEADLINE_MISSED = "mx_serving_deadline_missed_total"
SERVING_RETRIES = "mx_serving_retries_total"
SERVING_RECOVERIES = "mx_serving_recoveries_total"
SERVING_BREAKER_STATE = "mx_serving_breaker_state"
SERVING_DRAIN_SECONDS = "mx_serving_drain_seconds"

# ---------------------------------------------------------------------------
# continuous-batching decode engine (serving/decode.py + kvcache.py)
# ---------------------------------------------------------------------------
DECODE_TOKENS = "mx_decode_tokens_total"
DECODE_ACTIVE_SLOTS = "mx_decode_active_slots"
DECODE_KV_PAGES = "mx_decode_kv_pages"
DECODE_TTFT_SECONDS = "mx_decode_ttft_seconds"
DECODE_TPOT_SECONDS = "mx_decode_tpot_seconds"
DECODE_SPEC_DRAFTED = "mx_decode_spec_drafted_total"
DECODE_SPEC_ACCEPTED = "mx_decode_spec_accepted_total"
DECODE_PREFIX_HITS = "mx_decode_prefix_hits_total"
DECODE_COW_COPIES = "mx_decode_cow_copies_total"

# ---------------------------------------------------------------------------
# serving fleet controller (serving/fleet.py)
# ---------------------------------------------------------------------------
FLEET_REPLICAS = "mx_fleet_replicas"
FLEET_ROUTED = "mx_fleet_routed_requests_total"
FLEET_RESTARTS = "mx_fleet_replica_restarts_total"
FLEET_SWAPS = "mx_fleet_weight_swaps_total"
FLEET_SCALE_EVENTS = "mx_fleet_scale_events_total"
FLEET_QUEUE_WAIT = "mx_fleet_queue_wait_seconds"

# ---------------------------------------------------------------------------
# telemetry self-observation (telemetry/exporters.py)
# ---------------------------------------------------------------------------
HEARTBEATS = "mx_telemetry_heartbeats_total"

# ---------------------------------------------------------------------------
# thread/lock audit (analysis/threads.py)
# ---------------------------------------------------------------------------
THREADS_HELD = "mx_threads_held_locks"
THREADS_LONGEST_WAIT = "mx_threads_longest_wait_seconds"
THREADS_LOCK_WAIT = "mx_threads_lock_wait_seconds"
THREADS_DUMPS = "mx_threads_dumps_total"


#: name -> {kind, help, label}: the complete set of series the framework
#: may export. Registration of an unknown ``mx_*`` name raises.
CATALOG = {
    TRAIN_STEPS: dict(
        kind="counter", label=None,
        help="train steps dispatched through gluon.TrainLoop"),
    WINDOW_PUSHES: dict(
        kind="counter", label=None,
        help="async results pushed into any DispatchWindow"),
    WINDOW_RETIRES: dict(
        kind="counter", label=None,
        help="DispatchWindow FIFO retires (the designed blessed sync)"),
    WINDOW_ERRORS: dict(
        kind="counter", label=None,
        help="deferred async failures surfaced at a window retire"),
    WINDOW_OCCUPANCY: dict(
        kind="gauge", label=None,
        help="in-flight step futures currently outstanding"),
    WINDOW_CAPACITY: dict(
        kind="gauge", label=None,
        help="configured in-flight window bound (MXNET_INFLIGHT_STEPS)"),
    HOST_SYNCS: dict(
        kind="counter", label="kind",
        help="NDArray-level sync points by kind, process-wide across "
             "ALL threads (wait_to_read includes data-pipeline host "
             "reads on loader threads; window_retire = designed engine "
             "waits; guard.sync_counts() gives the per-thread hot-loop "
             "view)"),
    PREFETCH_BATCHES: dict(
        kind="counter", label=None,
        help="batches staged device-side by DevicePrefetcher"),
    PREFETCH_STARVATION: dict(
        kind="counter", label=None,
        help="times the consumer found the staging queue empty"),
    PREFETCH_INPUT_WAIT: dict(
        kind="counter", label=None,
        help="cumulative consumer-side wait on staged input, seconds"),
    COMPILE_CACHE_HITS: dict(
        kind="counter", label=None,
        help="persistent compilation cache hits (MXNET_COMPILE_CACHE)"),
    COMPILE_CACHE_MISSES: dict(
        kind="counter", label=None,
        help="persistent compilation cache misses"),
    COMPILE_CACHE_ENABLED: dict(
        kind="gauge", label=None,
        help="1 when the persistent compilation cache is armed"),
    COMPILE_RETRACES: dict(
        kind="counter", label=None,
        help="new compiled shape buckets built by Trainer.compile_step"),
    CHECKPOINT_SAVES: dict(
        kind="counter", label=None,
        help="checkpoints committed by TrainCheckpointManager"),
    CHECKPOINT_ERRORS: dict(
        kind="counter", label=None,
        help="failed checkpoint writes (surfaced on next save/wait)"),
    CHECKPOINT_RESTORES: dict(
        kind="counter", label=None,
        help="checkpoints applied by TrainCheckpointManager (auto-"
             "resume, elastic recovery, explicit restore)"),
    CHECKPOINT_RECOVERY_SECONDS: dict(
        kind="histogram", label=None,
        help="load+verify+apply latency of one checkpoint restore "
             "(the recovery-path critical section)"),
    ELASTIC_RECOVERIES: dict(
        kind="counter", label="cause",
        help="elastic supervisor recoveries by cause (device_lost, "
             "transient, stall, grow, preemption)"),
    ELASTIC_DOWNTIME_SECONDS: dict(
        kind="histogram", label=None,
        help="failure-to-resumed downtime of one elastic recovery "
             "(window discard + backoff + mesh re-form + recompile + "
             "restore)"),
    ELASTIC_WORLD_SIZE: dict(
        kind="gauge", label=None,
        help="devices in the currently-formed elastic world (shrinks "
             "on device loss, grows back on restore)"),
    ELASTIC_PREEMPTIONS: dict(
        kind="counter", label=None,
        help="preemption notices (SIGTERM/maintenance) that triggered "
             "a grace-window final checkpoint"),
    CHECKPOINT_CAPTURE_SECONDS: dict(
        kind="histogram", label=None,
        help="device->host state capture latency (pauses training)"),
    CHECKPOINT_SAVE_SECONDS: dict(
        kind="histogram", label=None,
        help="serialize+fsync+commit latency (overlapped, background)"),
    STEP_PHASE_SECONDS: dict(
        kind="histogram", label="phase",
        help="step-lifecycle phase durations (batch_fetch, h2d_wait, "
             "dispatch, window, retire, checkpoint)"),
    STEP_TIME_SECONDS: dict(
        kind="histogram", label=None,
        help="retire-to-retire step wall time (pipelined steady state)"),
    MODEL_FLOPS_PER_STEP: dict(
        kind="gauge", label=None,
        help="XLA cost_analysis FLOPs of one compiled train step"),
    MODEL_FLOPS_PER_SEC: dict(
        kind="gauge", label=None,
        help="flops_per_step / measured step time"),
    MFU: dict(
        kind="gauge", label=None,
        help="model FLOPs utilization vs the configured roofline"),
    STEP_TIME_EWMA: dict(
        kind="gauge", label=None,
        help="exponentially-weighted mean step time the stall detector "
             "compares against"),
    ANOMALIES: dict(
        kind="counter", label="kind",
        help="structured anomaly events by kind (nan_loss, stall, oom, "
             "memory_budget, device_lost, numerics divergence kinds)"),
    HBM_COMPILED_BYTES: dict(
        kind="gauge", label="component",
        help="compiled train-step memory_analysis bytes by component "
             "(argument, output, temp, generated_code, donated) — max "
             "over compiled shape buckets"),
    HBM_PEAK_BYTES: dict(
        kind="gauge", label=None,
        help="estimated peak HBM of one compiled train step: "
             "argument+output+temp+generated_code minus donated aliases"),
    MEM_POOL_BYTES: dict(
        kind="gauge", label="pool",
        help="live per-replica buffer bytes by census pool (params, "
             "optimizer, checkpoint, prefetch, kvcache, ndarray)"),
    MEM_POOL_BUFFERS: dict(
        kind="gauge", label="pool",
        help="live buffer count by census pool"),
    MEM_UNTRACKED_BYTES: dict(
        kind="gauge", label=None,
        help="jax.live_arrays() bytes NOT claimed by any census pool "
             "(suspected leaks / user temporaries)"),
    MEM_DEVICE_IN_USE: dict(
        kind="gauge", label="device",
        help="allocator bytes_in_use per device (live-array accounting "
             "on backends without allocator stats, e.g. XLA:CPU)"),
    MEM_DEVICE_PEAK: dict(
        kind="gauge", label="device",
        help="allocator peak_bytes_in_use per device (-1 where the "
             "backend exposes no high-water mark)"),
    MEM_DEVICE_LIMIT: dict(
        kind="gauge", label="device",
        help="allocator bytes_limit per device (-1 where unknown)"),
    MEM_BUDGET_BYTES: dict(
        kind="gauge", label=None,
        help="configured MXNET_MEMORY_BUDGET headroom bound in bytes"),
    OOM_DUMPS: dict(
        kind="counter", label=None,
        help="OOM post-mortem dump files written to "
             "MXNET_MEMORY_DUMP_DIR"),
    NUMERICS_GRAD_NORM: dict(
        kind="gauge", label=None,
        help="global L2 norm of the rescaled gradient of the last "
             "retired step (psum-composed in-program: exact under "
             "ZeRO/dp sharding)"),
    NUMERICS_PARAM_NORM: dict(
        kind="gauge", label=None,
        help="global L2 norm of the trainable parameters (fp32 masters "
             "under multi-precision) before the last retired update"),
    NUMERICS_GRAD_NORM_EWMA: dict(
        kind="gauge", label=None,
        help="exponentially-weighted mean grad norm the grad_spike "
             "detector compares against"),
    NUMERICS_UPDATE_RATIO: dict(
        kind="histogram", label=None,
        help="per-step update/weight ratio ||delta w|| / ||w|| "
             "distribution (healthy runs sit around 1e-3..1e-2)"),
    NUMERICS_LAYER_GRAD_NORM: dict(
        kind="gauge", label="param",
        help="per-parameter grad norm, top-K largest layers only "
             "(MXNET_NUMERICS=per_layer; bounded label cardinality)"),
    NUMERICS_MASTER_DRIFT: dict(
        kind="gauge", label=None,
        help="max relative drift between fp32 masters and their "
             "low-precision weight casts (ZeRO multi-precision units)"),
    NUMERICS_NONFINITE: dict(
        kind="counter", label="dtype",
        help="non-finite gradient elements observed at retires, by "
             "parameter dtype"),
    NUMERICS_DUMPS: dict(
        kind="counter", label=None,
        help="numerics post-mortem dump files written to "
             "MXNET_NUMERICS_DUMP_DIR"),
    FUSION_REGIONS: dict(
        kind="gauge", label=None,
        help="fusion kernels in the last-analyzed compiled step "
             "program (analysis/fusion.py census)"),
    FUSION_STRANDED: dict(
        kind="gauge", label=None,
        help="unfused elementwise/broadcast/convert ops stranded "
             "between two fusions above the size floor — each one two "
             "avoidable HBM round-trips per step"),
    FUSION_BOUNDARY_BYTES: dict(
        kind="gauge", label=None,
        help="intermediate bytes materialized at kernel boundaries of "
             "the last-analyzed step program (written to and re-read "
             "from HBM)"),
    FUSION_COMPUTE_BOUND: dict(
        kind="gauge", label=None,
        help="FLOP-weighted share (0-1) of kernels whose arithmetic "
             "intensity clears the measured roofline ridge point"),
    SHARDING_RESHARDS: dict(
        kind="gauge", label=None,
        help="SPMD-partitioner-inserted collectives in the last-"
             "analyzed program not implied by the declared spec, above "
             "the reshard byte floor (analysis/sharding.py)"),
    SHARDING_RESHARD_BYTES: dict(
        kind="gauge", label=None,
        help="wire bytes per step moved by implicit reshards of the "
             "last-analyzed program"),
    SHARDING_COMM_COST: dict(
        kind="gauge", label="axis",
        help="estimated per-step collective communication seconds by "
             "mesh axis (ring model over the MXNET_SHARDING_BANDWIDTH "
             "profile; '?' = unattributed groups)"),
    SHARDING_COLLECTIVE_BYTES: dict(
        kind="gauge", label="axis",
        help="ring-model wire bytes per step moved by collectives, by "
             "mesh axis"),
    SHARDING_EXPOSED_COMM: dict(
        kind="gauge", label="axis",
        help="exposed (non-overlapped) collective communication "
             "seconds per step by mesh axis, measured on the "
             "optimized-HLO schedule (analysis/overlap.py; '?' = "
             "unattributed groups)"),
    OVERLAP_FRACTION: dict(
        kind="gauge", label=None,
        help="share (0-1) of modeled collective seconds hidden behind "
             "independent compute in the last-analyzed program's "
             "schedule (0 = fully serial/exposed)"),
    KERNEL_DISPATCH: dict(
        kind="counter", label="path",
        help="Pallas kernel-layer dispatch decisions by path taken "
             "(pallas = compiled TPU kernel, interpret = kernel body "
             "under pallas interpret mode, xla = reference fallback; "
             "MXNET_PALLAS gate, docs/PERF_NOTES.md)"),
    AUTOTUNE_TRIALS: dict(
        kind="counter", label="backend",
        help="autotune candidate measurements by backend (timed = "
             "live warmup+measured executions, analytical = "
             "cost_analysis/memory model scoring; docs/PERF_NOTES.md "
             "\"Autotuner\")"),
    AUTOTUNE_CACHE_HITS: dict(
        kind="counter", label=None,
        help="autotune config-DB hits: a persisted winner replayed "
             "with zero trials (MXNET_AUTOTUNE_CACHE)"),
    AUTOTUNE_CACHE_MISSES: dict(
        kind="counter", label=None,
        help="autotune config-DB misses (mode=on searches; "
             "mode=cached falls back to the shipped defaults)"),
    AUTOTUNE_ACTIVE: dict(
        kind="gauge", label="tunable",
        help="active tuned-config info gauge: one series per applied "
             "tunable override (numeric values verbatim, choice "
             "values as their grid index)"),
    SERVING_REQUESTS: dict(
        kind="counter", label=None,
        help="inference requests submitted to any DynamicBatcher"),
    SERVING_BATCHES: dict(
        kind="counter", label=None,
        help="coalesced serving micro-batches dispatched"),
    SERVING_QUEUE_DEPTH: dict(
        kind="gauge", label=None,
        help="requests waiting to be coalesced (bounded queue + the "
             "forming batch; MXNET_SERVING_QUEUE_DEPTH caps it)"),
    SERVING_INFLIGHT: dict(
        kind="gauge", label=None,
        help="serving micro-batches in flight on the device (the "
             "batcher's DispatchWindow occupancy)"),
    SERVING_OCCUPANCY: dict(
        kind="histogram", label=None,
        help="per-micro-batch fill ratio: coalesced request rows / "
             "dispatched bucket rows (1.0 = no padding waste)"),
    SERVING_LATENCY: dict(
        kind="histogram", label=None,
        help="end-to-end request latency: submit to micro-batch "
             "retire (queueing + coalescing delay + compute)"),
    SERVING_REJECTED: dict(
        kind="counter", label="reason",
        help="requests shed at admission by reason (queue = bounded "
             "queue full, deadline = projected wait exceeds the "
             "request deadline, breaker = circuit breaker open during "
             "recovery, draining = graceful shutdown in progress, "
             "kvcache = decode KV page pool exhausted; "
             "MXNET_SERVING_SHED, docs/SERVING.md)"),
    SERVING_DEADLINE_MISSED: dict(
        kind="counter", label=None,
        help="accepted requests dropped at dequeue because their "
             "deadline expired while queued (failed with typed "
             "DeadlineExceeded, never padded/dispatched)"),
    SERVING_RETRIES: dict(
        kind="counter", label="cause",
        help="serving requests re-enqueued by the ServingSupervisor "
             "after a classified failure (device_lost = in-flight "
             "work re-dispatched post-recovery, transient = bounded "
             "backoff retry)"),
    SERVING_RECOVERIES: dict(
        kind="counter", label="cause",
        help="ServingSupervisor predictor rebuilds by failure cause "
             "(device_lost: re-formed over available_devices with AOT "
             "buckets warm-started from MXNET_COMPILE_CACHE)"),
    SERVING_BREAKER_STATE: dict(
        kind="gauge", label=None,
        help="serving circuit-breaker state: 0 closed (normal), 1 "
             "half-open (post-recovery probe), 2 open (fast-failing "
             "new submits while recovery runs)"),
    SERVING_DRAIN_SECONDS: dict(
        kind="histogram", label=None,
        help="graceful-drain duration: reject-new to queue flushed + "
             "in-flight retired + batcher closed (SIGTERM/preemption "
             "workflow, docs/SERVING.md)"),
    DECODE_TOKENS: dict(
        kind="counter", label=None,
        help="decode tokens delivered to streaming clients (useful "
             "tokens only: dropped post-EOS in-flight tokens excluded)"),
    DECODE_ACTIVE_SLOTS: dict(
        kind="gauge", label=None,
        help="batch slots occupied by a live request (prefilling or "
             "decoding) in the continuous-batching decode engine"),
    DECODE_KV_PAGES: dict(
        kind="gauge", label="state",
        help="paged-KV-cache page counts by state (used / free / "
             "shared — shared pages are mapped by >= 2 requests and "
             "counted once); bytes ride the kvcache census pool in "
             "mx_mem_pool_bytes"),
    DECODE_TTFT_SECONDS: dict(
        kind="histogram", label=None,
        help="time-to-first-token per decode request: admission to "
             "first streamed token retire (queueing + chunked prefill "
             "+ first step)"),
    DECODE_TPOT_SECONDS: dict(
        kind="histogram", label=None,
        help="time-per-output-token: inter-token gap between "
             "consecutive streamed tokens of one request (steady-state "
             "decode cadence)"),
    DECODE_SPEC_DRAFTED: dict(
        kind="counter", label=None,
        help="draft tokens proposed by the speculative-decode drafter "
             "(the guaranteed per-step token is not a draft and is "
             "excluded; acceptance rate = accepted / drafted)"),
    DECODE_SPEC_ACCEPTED: dict(
        kind="counter", label=None,
        help="draft tokens the verify scan accepted (longest prefix "
             "matching the model's own greedy continuation — the "
             "emitted stream stays bit-exact vs plain decode)"),
    DECODE_PREFIX_HITS: dict(
        kind="counter", label=None,
        help="requests seated onto shared prefix-cache pages (a "
             "registered prompt prefix matched byte-for-byte, so "
             "prefill skipped the shared region)"),
    DECODE_COW_COPIES: dict(
        kind="counter", label=None,
        help="copy-on-write page copies: a writer diverging on a "
             "shared KV page got a private copy before the write"),
    FLEET_REPLICAS: dict(
        kind="gauge", label="state",
        help="fleet replicas by lifecycle state (serving = in "
             "rotation, draining = flushing accepted requests before "
             "retire/swap, recovering = predictor rebuild after a "
             "replica loss, retired = out of the fleet for good)"),
    FLEET_ROUTED: dict(
        kind="counter", label="replica",
        help="requests the FleetRouter handed to each replica "
             "(lowest-projected-wait policy; an open breaker or a "
             "draining replica receives zero)"),
    FLEET_RESTARTS: dict(
        kind="counter", label=None,
        help="replica restarts after a replica loss (in-flight "
             "requests re-enqueued onto survivors; the dead replica "
             "rebuilt with bounded backoff on a spare device)"),
    FLEET_SWAPS: dict(
        kind="counter", label=None,
        help="zero-downtime rolling weight swaps completed "
             "(FleetController.swap_weights: drain one replica at a "
             "time, load the CRC-verified checkpoint, return to "
             "rotation)"),
    FLEET_SCALE_EVENTS: dict(
        kind="counter", label="direction",
        help="autoscale actions (up = replica added on queue-wait "
             "EWMA past MXNET_FLEET_SCALE_UP_WAIT_MS, down = emptiest "
             "replica drained-then-retired below the low-water mark)"),
    FLEET_QUEUE_WAIT: dict(
        kind="histogram", label=None,
        help="projected queue wait of the replica chosen at each "
             "routed submit — the fleet-wide load signal the "
             "autoscaler EWMAs"),
    THREADS_HELD: dict(
        kind="gauge", label=None,
        help="audited (mx_lock) locks currently held, process-wide"),
    THREADS_LONGEST_WAIT: dict(
        kind="gauge", label=None,
        help="longest single audited-lock wait observed since reset "
             "(updated live while a waiter is still blocked, so a "
             "wedged process shows its stall)"),
    THREADS_LOCK_WAIT: dict(
        kind="histogram", label="name",
        help="contended audited-lock acquisition wait per lock name"),
    THREADS_DUMPS: dict(
        kind="counter", label=None,
        help="deadlock/stall forensics dumps written to "
             "MXNET_THREADS_DUMP_DIR"),
    HEARTBEATS: dict(
        kind="counter", label=None,
        help="periodic telemetry heartbeat log lines emitted"),
}


def is_valid(name: str) -> bool:
    """Whether ``name`` matches the documented naming convention."""
    return bool(NAME_RE.match(name))


def kind_ok(name: str, kind: str) -> bool:
    """Kind-suffix rules: counters end ``_total``, histograms end in a
    unit suffix (``_seconds`` / ``_ratio``), gauges end in neither
    ``_total`` nor ``_bucket``."""
    if kind == "counter":
        return name.endswith("_total")
    if kind == "histogram":
        return name.endswith(("_seconds", "_ratio"))
    if kind == "gauge":
        return not name.endswith(("_total", "_bucket"))
    return False


def check(name: str, kind: str):
    """Registration-time validation (raises ``MXNetError``): convention
    regex + kind suffix for everyone; ``mx_``-prefixed names must also
    be declared in :data:`CATALOG` with a matching kind."""
    from ..base import MXNetError
    if not is_valid(name):
        raise MXNetError(
            f"metric name {name!r} violates the telemetry naming "
            f"convention {NAME_RE.pattern!r} (docs/OBSERVABILITY.md)")
    if not kind_ok(name, kind):
        raise MXNetError(
            f"metric {name!r} registered as {kind} violates the kind-"
            "suffix rule (counters *_total, histograms *_seconds; "
            "docs/OBSERVABILITY.md)")
    if name.startswith("mx_"):
        decl = CATALOG.get(name)
        if decl is None:
            raise MXNetError(
                f"metric {name!r} uses the framework prefix but is not "
                "declared in mxnet_tpu/telemetry/names.py CATALOG — add "
                "it there (single source of truth) before registering")
        if decl["kind"] != kind:
            raise MXNetError(
                f"metric {name!r} declared as {decl['kind']} in the "
                f"catalog but registered as {kind}")
