"""Public autograd API: ``record``/``pause``/``backward``/``grad``/``Function``.

Reference analog: python/mxnet/autograd.py (record :121, pause :145,
backward :245, grad :272, Function :369) over the C++ tape in
src/imperative/imperative.cc. The tape engine itself lives in _tape.py.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from . import _tape
from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "mark_variables",
           "suspend_taping", "backward", "grad", "get_symbol", "Function"]

is_recording = _tape.is_recording
is_training = _tape.is_training
set_recording = _tape.set_recording
set_training = _tape.set_training
mark_variables = _tape.mark_variables
# Whole-graph functionalization guard (cached ops, Trainer.compile_step):
# inside the scope is_recording() is forced False even if traced user code
# re-enters record() — jax differentiates the program; the tape must not.
suspend_taping = _tape.suspend_taping


class _RecordingStateScope:
    def __init__(self, is_record: Optional[bool], train_mode: Optional[bool]):
        self._enter_record = is_record
        self._enter_train = train_mode
        self._prev_record = None
        self._prev_train = None

    def __enter__(self):
        if self._enter_record is not None:
            self._prev_record = set_recording(self._enter_record)
        if self._enter_train is not None:
            self._prev_train = set_training(self._enter_train)
        return self

    def __exit__(self, *exc):
        if self._prev_record is not None and self._prev_record != self._enter_record:
            set_recording(self._prev_record)
        if self._prev_train is not None and self._prev_train != self._enter_train:
            set_training(self._prev_train)


def record(train_mode: bool = True):
    """Scope where ops are recorded to the tape (reference autograd.py:121)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    """Scope where recording is suspended (reference autograd.py:145)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    return _tape.backward(list(heads), head_grads, retain_graph=retain_graph,
                          train_mode=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables (reference autograd.py:272).
    create_graph=True records the backward pass for higher-order grads."""
    from .ndarray.ndarray import NDArray
    single = not isinstance(variables, (list, tuple))
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    var_list = [variables] if single else list(variables)
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    raw = _tape.grad(list(heads), var_list, head_grads, retain_graph,
                     create_graph, train_mode)
    out = [g if isinstance(g, NDArray) else NDArray(g) for g in raw]
    return out[0] if single else out


def get_symbol(x):
    """Reference autograd.get_symbol: symbolic view of a recorded array."""
    from .symbol.symbol import Symbol
    ent = getattr(x, "_tape_entry", None)
    if ent is None:
        raise MXNetError("array is not part of a recorded computation graph")
    return Symbol._from_tape(x)


class Function:
    """Custom differentiable function (reference autograd.py:369).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)``; both operate on NDArrays imperatively.
    """

    class _Registry:
        pass

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self.saved_tensors = arrays

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from ._tape import TapeNode, is_recording
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            import jax.numpy as jnp
            import jax

            func = self

            class _CustomNode(TapeNode):
                pass

            avals = [jax.ShapeDtypeStruct(o._data.shape, o._data.dtype)
                     for o in outs]

            def vjp_fn(cts):
                cts = cts if isinstance(cts, tuple) else (cts,)
                with pause():
                    gin = func.backward(*[NDArray(c) for c in cts])
                gin = gin if isinstance(gin, (list, tuple)) else (gin,)
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in gin)

            node = TapeNode(type(self).__name__, list(inputs), None, vjp_fn,
                            avals, out_is_tuple=not single)
            # create_graph path not supported for custom Functions (fn=None);
            # matches reference behavior (Function has no higher-order grad).
            for i, o in enumerate(outs):
                o._tape_entry = (node, i)
        return outs[0] if single else tuple(outs)

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
