"""ctypes bindings for the native runtime (src/native/).

Reference analog: python/mxnet/base.py's ctypes loader for libmxnet.so.
The native library provides the host-side runtime — threaded dependency
engine (versioned vars, exception propagation at sync points), RecordIO,
and a prefetching reader. It is built on demand with `make` (g++); when no
toolchain is available everything gracefully reports unavailable and pure-
Python fallbacks take over (recordio.py).

Set MXNET_TPU_NO_NATIVE=1 to force the pure-Python paths.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional, Sequence

from .base import MXNetError, get_env

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_REPO_ROOT, "build", "libmxt_native.so")
_SRC_DIR = os.path.join(_REPO_ROOT, "src", "native")

_lib = None
# bare on purpose: leaf guard below the audit layer (native library bootstrap)
_lib_lock = threading.Lock()  # mx-lint: allow=MXA009
_load_failed = False

_OP_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


def _build_lib() -> bool:
    try:
        r = subprocess.run(["make", "-C", _SRC_DIR],
                           capture_output=True, timeout=240)
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _declare(lib):
    lib.MXTGetLastError.restype = ctypes.c_char_p
    lib.MXTSetCallbackError.argtypes = [ctypes.c_char_p]
    H = ctypes.c_void_p
    lib.MXTEngineCreate.argtypes = [ctypes.c_int, ctypes.POINTER(H)]
    lib.MXTEngineDestroy.argtypes = [H]
    lib.MXTEngineNewVar.argtypes = [H, ctypes.POINTER(H)]
    lib.MXTEngineDeleteVar.argtypes = [H, H]
    lib.MXTEnginePushAsync.argtypes = [H, _OP_FN, ctypes.c_void_p,
                                       ctypes.c_void_p, ctypes.POINTER(H),
                                       ctypes.c_int, ctypes.POINTER(H),
                                       ctypes.c_int]
    lib.MXTEngineWaitForVar.argtypes = [H, H]
    lib.MXTEngineWaitForAll.argtypes = [H]
    lib.MXTEngineVarVersion.argtypes = [H, H,
                                        ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTRecordIOWriterCreate.argtypes = [ctypes.c_char_p,
                                            ctypes.POINTER(H)]
    lib.MXTRecordIOWriterWrite.argtypes = [H, ctypes.c_char_p,
                                           ctypes.c_size_t,
                                           ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTRecordIOWriterTell.argtypes = [H, ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTRecordIOWriterClose.argtypes = [H]
    lib.MXTRecordIOReaderCreate.argtypes = [ctypes.c_char_p,
                                            ctypes.POINTER(H)]
    lib.MXTRecordIOReaderNext.argtypes = [H, ctypes.POINTER(ctypes.c_void_p),
                                          ctypes.POINTER(ctypes.c_size_t)]
    lib.MXTRecordIOReaderSeek.argtypes = [H, ctypes.c_uint64]
    lib.MXTRecordIOReaderTell.argtypes = [H, ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTRecordIOReaderClose.argtypes = [H]
    lib.MXTPrefetchCreate.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.POINTER(H)]
    lib.MXTPrefetchNext.argtypes = [H, ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.POINTER(ctypes.c_size_t)]
    lib.MXTPrefetchDestroy.argtypes = [H]
    lib.MXTBatchifyStack.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_int]
    lib.MXTBatchifyImageNormalize.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int]
    try:
        # OPTIONAL symbols (need libjpeg at build time): a stale library
        # without them must not poison engine/recordio/batchify — image.py
        # hasattr-guards the decode fast path
        lib.MXTImageJPEGInfo.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.MXTImageJPEGDecode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    except AttributeError:
        pass
    try:
        lib.MXTImagePNGInfo.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.MXTImagePNGDecode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    except AttributeError:
        pass


def get_lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if get_env("MXNET_TPU_NO_NATIVE", "0") == "1":
            _load_failed = True
            return None
        # make is a fast no-op when the .so is current, and rebuilds it
        # when headers/sources changed (stale-symbol protection); a failed
        # build still falls through to an existing library
        if not _build_lib() and not os.path.exists(_LIB_PATH):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError):
            _load_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def _check(rc: int):
    if rc != 0:
        msg = get_lib().MXTGetLastError().decode() or "native call failed"
        raise MXNetError(msg)


class NativeEngine:
    """Host-task dependency engine (reference ThreadedEngine semantics:
    shared reads, exclusive writes per var, async exceptions surfacing at
    wait points). Schedules Python callables on C++ threads."""

    def __init__(self, num_threads: int = 0):
        lib = get_lib()
        if lib is None:
            raise MXNetError("native runtime not available")
        self._lib = lib
        h = ctypes.c_void_p()
        _check(lib.MXTEngineCreate(num_threads, ctypes.byref(h)))
        self._h = h
        self._closures = {}
        # bare on purpose: leaf, engine-internal; never nests with audited locks
        self._closure_lock = threading.Lock()  # mx-lint: allow=MXA009
        self._next_token = 1  # 0 would round-trip as NULL/None through ctypes

        def trampoline(token):
            with self._closure_lock:
                fn = self._closures.pop(token, None)
            if fn is None:
                return -1
            try:
                fn()
                return 0
            except Exception as e:  # surfaced at wait_for_var/wait_for_all
                self._lib.MXTSetCallbackError(
                    f"{type(e).__name__}: {e}".encode())
                return -1

        self._trampoline = _OP_FN(trampoline)  # keep alive

    def new_var(self) -> int:
        h = ctypes.c_void_p()
        _check(self._lib.MXTEngineNewVar(self._h, ctypes.byref(h)))
        return h.value

    def delete_var(self, var: int):
        _check(self._lib.MXTEngineDeleteVar(self._h, ctypes.c_void_p(var)))

    def push(self, fn: Callable[[], None],
             const_vars: Sequence[int] = (),
             mutable_vars: Sequence[int] = ()):
        """Schedule ``fn`` after its dependencies; reads run concurrently,
        writes exclusively (reference Engine::PushAsync)."""
        with self._closure_lock:
            token = self._next_token
            self._next_token += 1
            self._closures[token] = fn
        cv = (ctypes.c_void_p * max(len(const_vars), 1))(*const_vars)
        mv = (ctypes.c_void_p * max(len(mutable_vars), 1))(*mutable_vars)
        _check(self._lib.MXTEnginePushAsync(
            self._h, self._trampoline, ctypes.c_void_p(token), None,
            cv, len(const_vars), mv, len(mutable_vars)))

    def wait_for_var(self, var: int):
        _check(self._lib.MXTEngineWaitForVar(self._h, ctypes.c_void_p(var)))

    def wait_for_all(self):
        _check(self._lib.MXTEngineWaitForAll(self._h))

    def var_version(self, var: int) -> int:
        out = ctypes.c_uint64()
        _check(self._lib.MXTEngineVarVersion(self._h, ctypes.c_void_p(var),
                                             ctypes.byref(out)))
        return out.value

    def close(self):
        if self._h is not None:
            self._lib.MXTEngineDestroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordIOWriter:
    def __init__(self, path: str):
        self._lib = get_lib()
        h = ctypes.c_void_p()
        _check(self._lib.MXTRecordIOWriterCreate(path.encode(),
                                                 ctypes.byref(h)))
        self._h = h

    def write(self, data: bytes) -> int:
        pos = ctypes.c_uint64()
        _check(self._lib.MXTRecordIOWriterWrite(self._h, data, len(data),
                                                ctypes.byref(pos)))
        return pos.value

    def tell(self) -> int:
        out = ctypes.c_uint64()
        _check(self._lib.MXTRecordIOWriterTell(self._h, ctypes.byref(out)))
        return out.value

    def close(self):
        if self._h is not None:
            self._lib.MXTRecordIOWriterClose(self._h)
            self._h = None


class NativeRecordIOReader:
    def __init__(self, path: str):
        self._lib = get_lib()
        h = ctypes.c_void_p()
        _check(self._lib.MXTRecordIOReaderCreate(path.encode(),
                                                 ctypes.byref(h)))
        self._h = h

    def read(self) -> Optional[bytes]:
        data = ctypes.c_void_p()
        ln = ctypes.c_size_t()
        _check(self._lib.MXTRecordIOReaderNext(self._h, ctypes.byref(data),
                                               ctypes.byref(ln)))
        if data.value is None:
            return None
        return ctypes.string_at(data.value, ln.value)

    def seek(self, pos: int):
        _check(self._lib.MXTRecordIOReaderSeek(self._h, pos))

    def tell(self) -> int:
        out = ctypes.c_uint64()
        _check(self._lib.MXTRecordIOReaderTell(self._h, ctypes.byref(out)))
        return out.value

    def close(self):
        if self._h is not None:
            self._lib.MXTRecordIOReaderClose(self._h)
            self._h = None


class NativePrefetchReader:
    """C++ read-ahead thread over a RecordIO file (bounded queue)."""

    def __init__(self, path: str, capacity: int = 64):
        self._lib = get_lib()
        h = ctypes.c_void_p()
        _check(self._lib.MXTPrefetchCreate(path.encode(), capacity,
                                           ctypes.byref(h)))
        self._h = h

    def read(self) -> Optional[bytes]:
        data = ctypes.c_void_p()
        ln = ctypes.c_size_t()
        _check(self._lib.MXTPrefetchNext(self._h, ctypes.byref(data),
                                         ctypes.byref(ln)))
        if data.value is None:
            return None
        return ctypes.string_at(data.value, ln.value)

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h is not None:
            self._lib.MXTPrefetchDestroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
