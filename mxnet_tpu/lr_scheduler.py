"""Learning-rate schedulers (reference: python/mxnet/lr_scheduler.py)."""
from __future__ import annotations

import math

from .base import MXNetError

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "LinearWarmUp"]


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0.0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update: int) -> float:
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) * \
                num_update / max(self.warmup_steps, 1)
            return self.warmup_begin_lr + inc
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        raise MXNetError(f"bad warmup_mode {self.warmup_mode}")

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every `step` updates (reference FactorScheduler)."""

    def __init__(self, step: int, factor: float = 1.0, stop_factor_lr=1e-8,
                 base_lr=0.01, **kwargs):
        super().__init__(base_lr, **kwargs)
        if step < 1:
            raise MXNetError("step must be >= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        n = (num_update - self.warmup_steps) // self.step
        lr = self.base_lr * (self.factor ** n)
        return max(lr, self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, base_lr=0.01, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.step = sorted(step)
        self.factor = factor

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        lr = self.base_lr
        for s in self.step:
            if num_update >= s:
                lr *= self.factor
        return lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr=0.01, pwr=2, final_lr=0,
                 **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update >= self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / max(self.max_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            (1 - frac) ** self.power


class CosineScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr=0.01, final_lr=0, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update >= self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / max(self.max_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            (1 + math.cos(math.pi * frac)) / 2


class LinearWarmUp(LRScheduler):
    """Wrap another scheduler with linear warmup (gluon-nlp style)."""

    def __init__(self, schedule: LRScheduler, start_lr: float, length: int):
        super().__init__(schedule.base_lr)
        self.schedule = schedule
        self.start_lr = start_lr
        self.length = length

    def __call__(self, num_update: int) -> float:
        if num_update < self.length:
            return self.start_lr + (self.schedule(self.length) - self.start_lr) \
                * num_update / max(self.length, 1)
        return self.schedule(num_update)
