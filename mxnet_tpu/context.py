"""Device contexts: ``mx.cpu()``, ``mx.tpu(i)`` (and ``mx.gpu`` as an alias).

TPU-native re-design of the reference's Context (reference:
python/mxnet/context.py, include/mxnet/base.h Context struct). A Context names
a logical device; it resolves lazily to a ``jax.Device``. ``mx.tpu(i)`` is the
first-class accelerator context per the north star; ``mx.gpu(i)`` is kept as a
compatibility alias so reference user code runs unchanged.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = [
    "Context", "cpu", "tpu", "gpu", "cpu_pinned", "current_context",
    "num_tpus", "num_gpus", "device", "gpu_memory_info",
]

_DEVTYPE_CPU = 1
_DEVTYPE_TPU = 2  # occupies the accelerator slot the reference gives to kGPU
_DEVTYPE_CPU_PINNED = 3

_DEVTYPE_NAMES = {_DEVTYPE_CPU: "cpu", _DEVTYPE_TPU: "tpu",
                  _DEVTYPE_CPU_PINNED: "cpu_pinned"}


def _accelerator_devices():
    """Non-CPU jax devices addressable by THIS process, else local CPU
    devices (CPU-only test rigs). Local, not global: under jax.distributed a
    Context can only place data on this worker's own chips — the reference's
    ctx is likewise per-process (each worker addresses its own GPUs)."""
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return devs if devs else jax.local_devices()


class Context:
    """A logical device. Compares by (device_type, device_id) like the
    reference Context; ``ctx.jax_device`` resolves to the backing jax device.
    """

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type in ("gpu",):  # compat alias
            device_type = "tpu"
        if device_type not in ("cpu", "tpu", "cpu_pinned"):
            raise MXNetError(f"unknown device type {device_type!r}")
        if device_type == "tpu" and device_id != 0:
            # eager bounds check: a dangling tpu(i) would otherwise fail
            # far from its construction site (reference Context is lazy,
            # but its CUDA calls fail fast at first use on a bad ordinal)
            n = len(_accelerator_devices())
            if device_id >= n:
                raise MXNetError(
                    f"tpu({device_id}) requested but only {n} accelerator "
                    "device(s) present")
        self.device_type = device_type
        self.device_id = device_id
        self._old_ctx: Optional["Context"] = None

    @property
    def device_typeid(self) -> int:
        return {"cpu": _DEVTYPE_CPU, "tpu": _DEVTYPE_TPU,
                "cpu_pinned": _DEVTYPE_CPU_PINNED}[self.device_type]

    @property
    def jax_device(self) -> jax.Device:
        if self.device_type in ("cpu", "cpu_pinned"):
            cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
            if not cpus:
                # On a TPU-only runtime host staging still works via numpy;
                # map cpu ctx onto device 0 as the reference maps pinned mem.
                cpus = jax.local_devices()
            return cpus[min(self.device_id, len(cpus) - 1)]
        devs = _accelerator_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"tpu({self.device_id}) requested but only {len(devs)} "
                f"accelerator device(s) present")
        return devs[self.device_id]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        self._old_ctx = current_context()
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx

    # reference parity: Context.empty_cache frees the memory pool
    def empty_cache(self):
        """Release cached device memory (reference: context.py empty_cache).

        XLA/PjRt owns the allocator; this is a best-effort hint.
        """
        import gc
        gc.collect()


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value") or Context._default_ctx.value is None:
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias: reference code using mx.gpu(i) lands on tpu(i)."""
    return Context("tpu", device_id)


def device(dev_type: str, device_id: int = 0) -> Context:
    return Context(dev_type, device_id)


def num_tpus() -> int:
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return len(devs)


def num_gpus() -> int:  # compat alias used by reference scripts
    return num_tpus()


def gpu_memory_info(device_id=0):
    """CUDA memory query (reference context.py:249) — no analog on TPU
    builds; raises with the TPU-native alternative."""
    from .base import MXNetError
    raise MXNetError(
        "gpu_memory_info is CUDA-specific; use "
        "mx.profiler.memory_summary() for accelerator memory here")
