"""Generic class-factory registry.

Reference analog: python/mxnet/registry.py — per-base-class registries
with register / alias / create(name-or-config-JSON) factory functions.
Used by optimizer/initializer/lr_scheduler-style plugin surfaces.
"""
import json
import warnings

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]

_REGISTRY = {}


def get_registry(base_class):
    """A copy of the registry for ``base_class``."""
    return dict(_REGISTRY.setdefault(base_class, {}))


def get_register_func(base_class, nickname):
    """A registrator: ``register(klass, name=None)`` files subclasses of
    ``base_class`` under ``name.lower()`` (warning on override)."""
    registry = _REGISTRY.setdefault(base_class, {})

    def register(klass, name=None):
        if not (isinstance(klass, type) and issubclass(klass, base_class)):
            raise TypeError(
                f"Can only register subclass of {base_class.__name__}")
        key = (name or klass.__name__).lower()
        if key in registry:
            warnings.warn(
                f"New {nickname} {klass.__module__}.{klass.__name__} "
                f"registered with name {key} is overriding existing "
                f"{nickname} {registry[key].__module__}."
                f"{registry[key].__name__}", UserWarning, stacklevel=2)
        registry[key] = klass
        return klass

    register.__doc__ = f"Register {nickname} to the {nickname} factory"
    return register


def get_alias_func(base_class, nickname):
    """A decorator factory registering one class under several names:
    ``@alias('sgd', 'vanilla_sgd')``."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    """A factory: ``create(name, *args, **kwargs)`` instantiates the
    registered class. ``name`` may also be an instance (returned
    as-is), a config dict, or the JSON forms '["name", {...kwargs}]' /
    '{...kwargs incl. nickname key}' (reference registry.py:114)."""
    registry = _REGISTRY.setdefault(base_class, {})

    def create(*args, **kwargs):
        if args:
            name, args = args[0], args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            if args or kwargs:
                raise ValueError(
                    f"{nickname} is already an instance. Additional "
                    "arguments are invalid")
            return name
        if isinstance(name, dict):
            return create(**name)
        if not isinstance(name, str):
            raise TypeError(f"{nickname} must be of string type")
        if name.startswith("["):
            if args or kwargs:
                raise ValueError("JSON config takes no extra arguments")
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith("{"):
            if args or kwargs:
                raise ValueError("JSON config takes no extra arguments")
            return create(**json.loads(name))
        key = name.lower()
        if key not in registry:
            raise KeyError(
                f"{name} is not registered. Please register with "
                f"{nickname}.register first")
        return registry[key](*args, **kwargs)

    create.__doc__ = f"Create a {nickname} instance from config."
    return create
