"""Fault-injection harness for crash-consistency testing.

TPU pods are preempted, hosts are killed mid-write, and NFS hiccups —
the TensorFlow paper (arXiv:1605.08695 §4.3) makes periodic
checkpoint/restore the canonical answer, which is only trustworthy if
the checkpoint path itself survives being killed at its worst moment.
This module plants named *fault points* inside the persistence stack
(``checkpoint.stage``, ``checkpoint.commit``, ``checkpoint.prune``,
``ndarray.save``, ...) that are inert by default and armed through one
env var::

    MXNET_FAULT_INJECT="checkpoint.commit:after=1"          # SIGKILL
    MXNET_FAULT_INJECT="checkpoint.stage:before=2:error"    # raise IO error
    MXNET_FAULT_INJECT="ndarray.save:before=1:delay:250"    # sleep 250ms

Grammar (``;``-separated rules)::

    rule   := point ':' phase '=' nth [':' action]
    phase  := 'before' | 'after'     # relative to the guarded operation
    nth    := 1-based hit count at which the rule fires (once)
    action := 'kill'                 # os.kill(SIGKILL) — hard preemption
            | 'error'                # raise FaultInjectedError (an OSError)
            | 'delay' ':' millis     # sleep, for overlap/race windows

Subprocess kill-9 tests (tests/test_checkpoint.py) set the env var,
run a real training loop, get SIGKILLed mid-commit, and then prove the
checkpoint directory still resumes bit-exactly.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["fault_point", "FaultInjectedError", "FaultRule", "configure",
           "reset", "hit_counts"]

_LOG = logging.getLogger("mxnet_tpu.faults")

ENV_VAR = "MXNET_FAULT_INJECT"


class FaultInjectedError(OSError):
    """The injected IO failure (an ``OSError`` so generic ``except OSError``
    recovery paths are exercised exactly like a real disk error)."""


class FaultRule:
    __slots__ = ("point", "phase", "nth", "action", "delay_ms", "fired")

    def __init__(self, point: str, phase: str, nth: int, action: str,
                 delay_ms: int = 0):
        if phase not in ("before", "after"):
            raise ValueError(f"fault phase must be before/after, got {phase!r}")
        if action not in ("kill", "error", "delay"):
            raise ValueError(f"unknown fault action {action!r}")
        self.point = point
        self.phase = phase
        self.nth = int(nth)
        self.action = action
        self.delay_ms = int(delay_ms)
        self.fired = False

    def __repr__(self):
        return (f"FaultRule({self.point}:{self.phase}={self.nth}"
                f":{self.action})")


def _parse(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for chunk in spec.replace(",", ";").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) < 2 or "=" not in parts[1]:
            raise ValueError(
                f"bad {ENV_VAR} rule {chunk!r}; expected "
                "'point:before|after=N[:kill|error|delay:MS]'")
        point = parts[0]
        phase, nth = parts[1].split("=", 1)
        action = parts[2] if len(parts) > 2 else "kill"
        delay_ms = int(parts[3]) if action == "delay" and len(parts) > 3 \
            else 0
        rules.append(FaultRule(point, phase.strip(), int(nth), action,
                               delay_ms))
    return rules


# (point, phase) -> hit count; rules parsed once per process (subprocess
# tests re-exec with the env var set) or overridden via configure()
_lock = threading.Lock()
_rules: Optional[List[FaultRule]] = None
_counts: Dict[Tuple[str, str], int] = {}


def _get_rules() -> List[FaultRule]:
    global _rules
    if _rules is None:
        spec = os.environ.get(ENV_VAR, "")
        _rules = _parse(spec) if spec else []
        if _rules:
            _LOG.warning("fault injection ARMED: %s", _rules)
    return _rules


def configure(spec: Optional[str]) -> List[FaultRule]:
    """Arm (or, with None/'', disarm) fault rules in-process, bypassing
    the env var — the unit-test entry point."""
    global _rules
    with _lock:
        _rules = _parse(spec) if spec else []
        _counts.clear()
        return _rules


def reset():
    """Disarm everything and forget hit counts (returns to env parsing)."""
    global _rules
    with _lock:
        _rules = None
        _counts.clear()


def hit_counts() -> Dict[Tuple[str, str], int]:
    return dict(_counts)


def fault_point(point: str, phase: str = "before"):
    """Declare a named fault point. Call sites bracket a critical
    operation::

        fault_point("checkpoint.commit", "before")
        os.replace(tmp, final)
        fault_point("checkpoint.commit", "after")

    Inert (one dict lookup) unless ``MXNET_FAULT_INJECT``/``configure``
    armed a matching rule.
    """
    rules = _get_rules()
    if not rules:
        return
    with _lock:
        key = (point, phase)
        _counts[key] = n = _counts.get(key, 0) + 1
        to_fire = [r for r in rules
                   if r.point == point and r.phase == phase
                   and not r.fired and r.nth == n]
        for r in to_fire:
            r.fired = True
    for r in to_fire:
        _fire(r)


def _fire(rule: FaultRule):
    _LOG.warning("fault injection FIRING %r", rule)
    if rule.action == "kill":
        # the hard preemption: no atexit, no finally, no flush — exactly
        # what a pod eviction or OOM-kill does to the process
        os.kill(os.getpid(), signal.SIGKILL)
    elif rule.action == "error":
        raise FaultInjectedError(
            f"injected IO failure at {rule.point}:{rule.phase}")
    elif rule.action == "delay":
        time.sleep(rule.delay_ms / 1000.0)
