"""Fault-injection harness for crash-consistency testing.

TPU pods are preempted, hosts are killed mid-write, and NFS hiccups —
the TensorFlow paper (arXiv:1605.08695 §4.3) makes periodic
checkpoint/restore the canonical answer, which is only trustworthy if
the checkpoint path itself survives being killed at its worst moment.
This module plants named *fault points* inside the persistence stack
(``checkpoint.stage``, ``checkpoint.commit``, ``checkpoint.prune``,
``ndarray.save``, ...) that are inert by default and armed through one
env var::

    MXNET_FAULT_INJECT="checkpoint.commit:after=1"          # SIGKILL
    MXNET_FAULT_INJECT="checkpoint.stage:before=2:error"    # raise IO error
    MXNET_FAULT_INJECT="ndarray.save:before=1:delay:250"    # sleep 250ms
    MXNET_FAULT_INJECT="step.dispatch:before=6:revoke:4"    # lose 4 devices

Grammar (``;``-separated rules)::

    rule   := point ['@' ctx] ':' phase '=' nth [':' action]
    ctx    := caller-supplied context tag (e.g. a fleet replica name):
              the rule fires on the nth hit AT THAT CONTEXT only —
              ``fault_point(point, phase, ctx=...)`` call sites opt in;
              rules without '@' match every context (legacy behavior)
    phase  := 'before' | 'after'     # relative to the guarded operation
    nth    := 1-based hit count at which the rule fires (once)
    action := 'kill'                 # os.kill(SIGKILL) — hard preemption
            | 'error'                # raise FaultInjectedError (an OSError)
            | 'delay' ':' millis     # sleep, for overlap/race windows
            | 'revoke' [':' count]   # mark `count` devices (default 1)
                                     # revoked and raise DeviceRevokedError
                                     # — a mid-run device loss
            | 'revoke' ':' targets   # targets := 'd' id ['+' 'd' id ...]
                                     # revoke SPECIFIC device ids (the
                                     # fleet's replica-targeted kill)
            | 'restore'              # un-revoke every revoked device (the
                                     # chaos "grow back"); does not raise

Subprocess kill-9 tests (tests/test_checkpoint.py) set the env var,
run a real training loop, get SIGKILLed mid-commit, and then prove the
checkpoint directory still resumes bit-exactly.

The ``revoke``/``restore`` pair is the elastic chaos harness
(docs/ROBUSTNESS.md "Elastic training"): ``revoke`` marks the LAST
``count`` still-alive devices revoked — ``parallel.dist
.available_devices()`` excludes them, so the elastic supervisor's mesh
re-formation sees a genuinely smaller world — and raises a
:class:`DeviceRevokedError` whose message mimics the PjRt device-lost
pattern the real hardware produces. ``restore`` clears the revoked set
so a later ``world_changed()`` probe sees the world grow back. The
fault points bracketing step dispatch (``step.dispatch``), window
retire (``window.retire``) and device_put staging (``prefetch.stage``)
are where mid-run revocations land.

The SERVING chaos seams (docs/SERVING.md "Resilient serving") mirror
them on the inference path: ``serving.admit`` (inside
``DynamicBatcher.submit``, before admission control),
``serving.dispatch`` (just before the coalesced micro-batch's
predictor call), ``serving.retire`` (inside the window-retire sync
on the micro-batch's outputs) and ``serving.route`` (inside
``FleetRouter.submit``, after the replica was chosen — fired with
``ctx=<replica name>``). A ``revoke`` at dispatch/retire
is what the :class:`~mxnet_tpu.serving.ServingSupervisor`'s
device-loss recovery is tested against (tests/
test_serving_resilience.py); a replica-targeted rule like
``serving.dispatch@replica-1:before=1:revoke:d3`` is the FLEET chaos
harness — it fires only on that replica's dispatcher thread and
revokes that replica's device, so the fleet's failover (re-route
in-flight onto survivors, restart the replica) is what recovers
(tests/test_fleet.py).
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["fault_point", "FaultInjectedError", "DeviceRevokedError",
           "FaultRule", "configure", "reset", "hit_counts",
           "revoked_device_ids", "restore_devices"]

_LOG = logging.getLogger("mxnet_tpu.faults")

ENV_VAR = "MXNET_FAULT_INJECT"


class FaultInjectedError(OSError):
    """The injected IO failure (an ``OSError`` so generic ``except OSError``
    recovery paths are exercised exactly like a real disk error)."""


class DeviceRevokedError(RuntimeError):
    """The injected device loss: message mimics the PjRt/XlaRuntimeError
    device-lost pattern, so ``elastic.detect.is_device_lost`` classifies
    it exactly like the real thing (a ``RuntimeError`` because that is
    what jaxlib surfaces for execution failures)."""


class FaultRule:
    __slots__ = ("point", "phase", "nth", "action", "delay_ms", "count",
                 "ctx", "device_ids", "fired")

    def __init__(self, point: str, phase: str, nth: int, action: str,
                 delay_ms: int = 0, count: int = 1,
                 ctx: Optional[str] = None, device_ids=None):
        if phase not in ("before", "after"):
            raise ValueError(f"fault phase must be before/after, got {phase!r}")
        if action not in ("kill", "error", "delay", "revoke", "restore"):
            raise ValueError(f"unknown fault action {action!r}")
        self.point = point
        self.phase = phase
        self.nth = int(nth)
        self.action = action
        self.delay_ms = int(delay_ms)
        self.count = max(1, int(count))
        self.ctx = ctx               # None = match every context
        self.device_ids = tuple(device_ids) if device_ids else None
        self.fired = False

    def __repr__(self):
        at = f"@{self.ctx}" if self.ctx else ""
        return (f"FaultRule({self.point}{at}:{self.phase}={self.nth}"
                f":{self.action})")


def _parse_revoke_arg(arg: str):
    """``revoke``'s optional argument: a plain count, or 'd<id>'
    (+-joined for several) naming SPECIFIC device ids to revoke."""
    if arg and arg.lstrip().startswith("d"):
        ids = []
        for tok in arg.split("+"):
            tok = tok.strip()
            if not tok.startswith("d"):
                raise ValueError(
                    f"bad revoke target {tok!r}; expected 'd<id>'")
            ids.append(int(tok[1:]))
        return 1, tuple(ids)
    return int(arg), None


def _parse(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for chunk in spec.replace(",", ";").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) < 2 or "=" not in parts[1]:
            raise ValueError(
                f"bad {ENV_VAR} rule {chunk!r}; expected "
                "'point[@ctx]:before|after=N[:kill|error|delay:MS"
                "|revoke[:COUNT|:dID]]'")
        point, ctx = parts[0], None
        if "@" in point:
            point, ctx = point.split("@", 1)
        phase, nth = parts[1].split("=", 1)
        action = parts[2] if len(parts) > 2 else "kill"
        delay_ms = int(parts[3]) if action == "delay" and len(parts) > 3 \
            else 0
        count, device_ids = 1, None
        if action == "revoke" and len(parts) > 3:
            count, device_ids = _parse_revoke_arg(parts[3])
        rules.append(FaultRule(point, phase.strip(), int(nth), action,
                               delay_ms, count, ctx=ctx,
                               device_ids=device_ids))
    return rules


# (point, phase) -> hit count; rules parsed once per process (subprocess
# tests re-exec with the env var set) or overridden via configure()
# bare on purpose: fault points fire inside audited sections; auditing recurses
_lock = threading.Lock()  # mx-lint: allow=MXA009
_rules: Optional[List[FaultRule]] = None
_counts: Dict[Tuple[str, str], int] = {}


def _get_rules() -> List[FaultRule]:
    global _rules
    if _rules is None:
        spec = os.environ.get(ENV_VAR, "")
        _rules = _parse(spec) if spec else []
        if _rules:
            _LOG.warning("fault injection ARMED: %s", _rules)
    return _rules


def configure(spec: Optional[str]) -> List[FaultRule]:
    """Arm (or, with None/'', disarm) fault rules in-process, bypassing
    the env var — the unit-test entry point."""
    global _rules
    with _lock:
        _rules = _parse(spec) if spec else []
        _counts.clear()
        return _rules


def reset():
    """Disarm everything, forget hit counts, and restore revoked devices
    (returns to env parsing)."""
    global _rules
    with _lock:
        _rules = None
        _counts.clear()
        _revoked.clear()


# ---------------------------------------------------------------- revocation
# device ids the chaos harness marked lost; parallel.dist
# .available_devices() excludes them so mesh re-formation sees the
# surviving world
_revoked: set = set()


def revoked_device_ids() -> frozenset:
    """Ids of devices a ``revoke`` fault marked lost (empty normally)."""
    with _lock:
        return frozenset(_revoked)


def restore_devices(ids=None):
    """Un-revoke devices (all of them by default) — the chaos-harness
    "grow back"; also fired by the ``restore`` fault action."""
    with _lock:
        if ids is None:
            _revoked.clear()
        else:
            _revoked.difference_update(ids)


def _revoke_devices(count: int):
    """Mark the LAST ``count`` still-alive devices revoked (at least one
    device always survives) and return the lost ones."""
    import jax
    with _lock:
        alive = [d for d in jax.devices() if d.id not in _revoked]
        lost = alive[max(1, len(alive) - count):]
        _revoked.update(d.id for d in lost)
    return lost


def _revoke_specific(ids):
    """Mark SPECIFIC device ids revoked (the fleet's replica-targeted
    kill); at least one device always survives. Returns the lost
    devices."""
    import jax
    wanted = set(ids)
    with _lock:
        alive = [d for d in jax.devices() if d.id not in _revoked]
        lost = [d for d in alive if d.id in wanted]
        lost = lost[:max(0, len(alive) - 1)]
        _revoked.update(d.id for d in lost)
    return lost


def hit_counts() -> Dict[Tuple[str, str], int]:
    return dict(_counts)


def fault_point(point: str, phase: str = "before",
                ctx: Optional[str] = None):
    """Declare a named fault point. Call sites bracket a critical
    operation::

        fault_point("checkpoint.commit", "before")
        os.replace(tmp, final)
        fault_point("checkpoint.commit", "after")

    ``ctx`` tags the call with a caller context (e.g. a fleet replica
    name): ``point@ctx`` rules fire on the nth hit AT that context
    only; context-less rules keep matching every hit.

    Inert (one dict lookup) unless ``MXNET_FAULT_INJECT``/``configure``
    armed a matching rule.
    """
    rules = _get_rules()
    if not rules:
        return
    with _lock:
        key = (point, phase)
        _counts[key] = n = _counts.get(key, 0) + 1
        nc = None
        if ctx is not None:
            ckey = (point, phase, ctx)
            _counts[ckey] = nc = _counts.get(ckey, 0) + 1
        to_fire = [r for r in rules
                   if r.point == point and r.phase == phase
                   and not r.fired
                   and (r.nth == n if r.ctx is None
                        else (r.ctx == ctx and r.nth == nc))]
        for r in to_fire:
            r.fired = True
    for r in to_fire:
        _fire(r)


def _fire(rule: FaultRule):
    _LOG.warning("fault injection FIRING %r", rule)
    if rule.action == "kill":
        # the hard preemption: no atexit, no finally, no flush — exactly
        # what a pod eviction or OOM-kill does to the process
        os.kill(os.getpid(), signal.SIGKILL)
    elif rule.action == "error":
        raise FaultInjectedError(
            f"injected IO failure at {rule.point}:{rule.phase}")
    elif rule.action == "delay":
        time.sleep(rule.delay_ms / 1000.0)
    elif rule.action == "revoke":
        lost = _revoke_specific(rule.device_ids) if rule.device_ids \
            else _revoke_devices(rule.count)
        # a single-device world has nothing to revoke (>= 1 always
        # survives) but the failure is still injected — name it so
        names = ", ".join(str(d) for d in lost) \
            or "<none revocable: single-device world>"
        # the message mirrors what PjRt surfaces when a TPU host is
        # preempted mid-execution, so detection pattern-matches reality
        raise DeviceRevokedError(
            f"INTERNAL: device lost: {names} removed from the system; "
            f"execution aborted (injected revocation at "
            f"{rule.point}:{rule.phase})")
    elif rule.action == "restore":
        restore_devices()
