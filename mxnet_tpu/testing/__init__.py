"""Testing support: fault injection and deterministic scheduling.

Reference analog: the reference's test fault tooling is ad-hoc
(tests/python/unittest/common.py retry decorators); here fault points
are first-class so the checkpoint stack's atomicity claims are enforced
by kill-9 tests instead of asserted in comments. :mod:`.sched` (lazy —
it pulls in the analysis layer) adds the deterministic-schedule
harness: seeded, replayable thread interleavings over the audited
locks of ``analysis/threads.py``.
"""
from . import faults                              # noqa: F401
from .faults import (fault_point, FaultInjectedError,  # noqa: F401
                     DeviceRevokedError, FaultRule)

__all__ = ["faults", "fault_point", "FaultInjectedError",
           "DeviceRevokedError", "FaultRule", "sched"]


def __getattr__(name):
    if name == "sched":
        import importlib
        return importlib.import_module(".sched", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
