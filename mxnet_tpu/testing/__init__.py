"""Testing support: fault injection for crash-consistency proofs.

Reference analog: the reference's test fault tooling is ad-hoc
(tests/python/unittest/common.py retry decorators); here fault points
are first-class so the checkpoint stack's atomicity claims are enforced
by kill-9 tests instead of asserted in comments.
"""
from . import faults                              # noqa: F401
from .faults import (fault_point, FaultInjectedError,  # noqa: F401
                     DeviceRevokedError, FaultRule)

__all__ = ["faults", "fault_point", "FaultInjectedError",
           "DeviceRevokedError", "FaultRule"]
