"""Deterministic-schedule harness: seeded, replayable thread interleavings.

Wall-clock threaded tests prove a race exists roughly never and prove
its absence exactly never. This harness makes small-schedule exhaustion
possible instead: real OS threads run the REAL code under test, but a
:class:`VirtualScheduler` holds them all parked except one, choosing
which runs next from a seeded RNG at every yield point. The same seed
replays the same interleaving; 64+ seeds sweep the schedule space.

Yield points come from the audited primitives in
``analysis/threads.py``: while a scheduler is installed
(``threads.set_scheduler``), every ``mx_lock`` acquire/release and
``MxCondition`` wait/notify on a thread the scheduler MANAGES parks the
thread and hands control back. Unmanaged threads (pytest's main thread,
real daemons) keep real blocking semantics. :class:`SchedQueue` extends
the yield points to queue get/put, and :meth:`VirtualScheduler.checkpoint`
marks explicit schedule points in test bodies.

Blocking under the scheduler is VIRTUAL: a managed thread never really
blocks on a lock/condition/queue — it parks with a ``blocked`` note and
only becomes runnable again when the resource frees (owner released,
notify arrived, queue non-empty). If every live task is blocked the
harness raises :class:`SchedDeadlock` naming each task's obstacle — a
real deadlock caught in microseconds instead of a hung CI job. Timed
waits are modeled as "the timeout may expire whenever the scheduler
says so": a timed cond/lock/queue wait is always schedulable and
returns its timeout outcome if the resource is still unavailable.

Typical shape::

    def body_a(): ...            # real code under test
    def body_b(): ...
    for seed in range(64):
        s = VirtualScheduler(seed=seed)
        s.spawn("a", body_a)
        s.spawn("b", body_b)
        s.run()                  # replays one interleaving; reraises
        assert invariant_holds() # task exceptions with the trace
"""
from __future__ import annotations

import functools
import queue
import random
import threading
from typing import Callable, List, Optional

from ..analysis import threads as _threads

__all__ = ["VirtualScheduler", "SchedError", "SchedDeadlock",
           "SchedQueue", "explore"]

#: real-time guard on every park/handoff — only trips when the code
#: under test escapes the harness (blocks outside an audited primitive)
_HANDOFF_TIMEOUT = 30.0


class SchedError(RuntimeError):
    """Harness failure: step bound exceeded, task escaped, misuse."""


class SchedDeadlock(SchedError):
    """Every live task is blocked — an actual deadlock in the schedule."""


class _SchedAbort(BaseException):
    """Raised inside straggler tasks on the failure path so their
    ``with lock:`` frames unwind (releasing the raw locks) instead of
    retrying real blocking acquires and wedging until the join
    timeout. BaseException so test-body ``except Exception`` handlers
    cannot swallow it."""


class _Task:
    __slots__ = ("name", "fn", "go", "parked", "finished", "exc",
                 "blocked", "notified", "timed", "thread")

    def __init__(self, name: str, fn: Callable[[], None]):
        self.name = name
        self.fn = fn
        self.go = threading.Event()
        self.parked = threading.Event()
        self.finished = False
        self.exc: Optional[BaseException] = None
        #: None | ("lock", MxLock) | ("cond", MxCondition)
        #: | ("queue", SchedQueue, "get"/"put")
        self.blocked = None
        self.notified = False
        self.timed = False
        self.thread: Optional[threading.Thread] = None


class VirtualScheduler:
    """One seeded interleaving over a set of spawned task bodies.

    Exactly one managed thread runs at any moment; control transfers
    through Event handshakes at every audited-primitive yield point, so
    the scheduler observes a QUIESCENT system (all tasks parked) at
    each scheduling decision — task state reads race-free by
    construction."""

    def __init__(self, seed: int = 0, max_steps: int = 50000,
                 name: str = "sched"):
        self.seed = seed
        self.rng = random.Random(seed)
        self.max_steps = max_steps
        self.name = name
        self.tasks: List[_Task] = []
        self._by_ident = {}
        self.trace: List[str] = []
        self.steps = 0
        self._started = False
        self._aborting = False

    # ------------- setup -------------
    def spawn(self, name: str, fn: Callable, *args, **kwargs) -> _Task:
        if self._started:
            raise SchedError("spawn() after run()")
        if args or kwargs:
            fn = functools.partial(fn, *args, **kwargs)
        t = _Task(name, fn)
        self.tasks.append(t)
        return t

    def manages_current_thread(self) -> bool:
        return threading.get_ident() in self._by_ident

    # ------------- task side (runs on managed threads) -------------
    def _current(self) -> _Task:
        return self._by_ident[threading.get_ident()]

    def _park(self, task: _Task, blocked=None):
        task.blocked = blocked
        task.parked.set()
        task.go.wait()
        task.go.clear()
        task.blocked = None
        if self._aborting:
            raise _SchedAbort()

    def yield_point(self):
        """Hand control back to the scheduler (threads.py calls this
        after every audited release)."""
        self._park(self._current())

    #: explicit schedule point for test bodies
    checkpoint = yield_point

    def acquire_lock(self, lk, blocking: bool = True,
                     timeout: float = -1) -> bool:
        task = self._current()
        self._park(task)                # pre-acquire schedule point
        timed = timeout is not None and timeout >= 0
        while True:
            if lk._raw.acquire(False):
                return True
            if not blocking:
                return False
            if timed:
                # virtual expiry: one more schedule round, then the
                # timeout "fires" if the lock is still held
                self._park(task)
                if lk._raw.acquire(False):
                    return True
                return False
            self._park(task, blocked=("lock", lk))

    def cond_wait(self, cond, timeout: Optional[float] = None) -> bool:
        task = self._current()
        entry = cond._lock._sched_release_for_wait()
        task.notified = False
        task.timed = timeout is not None
        self._park(task, blocked=("cond", cond))
        got = task.notified
        task.notified = False
        task.timed = False
        cond._lock._sched_reacquire_after_wait(entry)
        return got

    def cond_notify(self, cond, n: Optional[int] = 1):
        """Mark up to ``n`` (None = all) virtual waiters on ``cond``
        notified-and-runnable. Safe from managed AND unmanaged threads:
        waiters are parked, so their ``blocked`` notes are stable."""
        waiters = [t for t in self.tasks
                   if t.blocked is not None and t.blocked[0] == "cond"
                   and t.blocked[1] is cond and not t.notified]
        if n is None:
            n = len(waiters)
        for t in waiters[:n]:
            t.notified = True

    # ------------- scheduler side -------------
    def _runnable(self, t: _Task) -> bool:
        b = t.blocked
        if b is None:
            return True
        kind = b[0]
        if kind == "lock":
            return b[1]._owner is None
        if kind == "cond":
            return t.notified or t.timed
        if kind == "queue":
            q, op = b[1], b[2]
            if op == "get":
                return q.qsize() > 0
            return q.maxsize <= 0 or q.qsize() < q.maxsize
        return True         # pragma: no cover - unknown kinds run

    def _deadlock_message(self, live: List[_Task]) -> str:
        bits = []
        for t in live:
            b = t.blocked
            if b is None:
                desc = "runnable?"      # pragma: no cover
            elif b[0] == "lock":
                lk = b[1]
                desc = (f"blocked on mx_lock {lk.name!r} "
                        f"(owner: {lk._owner_name!r})")
            elif b[0] == "cond":
                desc = f"waiting on condition {b[1].name!r} (no notify)"
            else:
                desc = f"blocked on queue {b[0:3]!r}"
            bits.append(f"{t.name}: {desc}")
        return (f"schedule deadlock (seed={self.seed}, "
                f"step={self.steps}): " + "; ".join(bits)
                + f"; trace tail={self.trace[-12:]}")

    def run(self) -> "VirtualScheduler":
        """Replay one interleaving to completion; reraises the first
        task exception. One-shot."""
        if self._started:
            raise SchedError("run() is one-shot; build a new scheduler")
        self._started = True
        if _threads.scheduler() is not None:
            raise SchedError("another VirtualScheduler is installed")
        _threads.set_scheduler(self)
        try:
            for task in self.tasks:
                th = threading.Thread(
                    target=self._bootstrap, args=(task,),
                    name=f"{self.name}:{task.name}", daemon=True)
                task.thread = th
                th.start()
                if not task.parked.wait(_HANDOFF_TIMEOUT):
                    raise SchedError(
                        f"task {task.name!r} failed to start")
            while True:
                live = [t for t in self.tasks if not t.finished]
                if not live:
                    break
                runnable = [t for t in live if self._runnable(t)]
                if not runnable:
                    raise SchedDeadlock(self._deadlock_message(live))
                t = self.rng.choice(runnable)
                self.steps += 1
                if self.steps > self.max_steps:
                    raise SchedError(
                        f"schedule exceeded {self.max_steps} steps "
                        f"(seed={self.seed}; livelock? trace tail="
                        f"{self.trace[-20:]})")
                self.trace.append(t.name)
                t.parked.clear()
                t.go.set()
                if not t.parked.wait(_HANDOFF_TIMEOUT):
                    raise SchedError(
                        f"task {t.name!r} did not yield within "
                        f"{_HANDOFF_TIMEOUT}s (seed={self.seed}) — "
                        "blocked outside an audited primitive?")
        finally:
            _threads.set_scheduler(None)
            self._release_stragglers()
        for t in self.tasks:
            if t.exc is not None:
                raise AssertionError(
                    f"task {t.name!r} failed under seed {self.seed} "
                    f"(trace={self.trace}): "
                    f"{type(t.exc).__name__}: {t.exc}") from t.exc
        return self

    def _bootstrap(self, task: _Task):
        ident = threading.get_ident()
        self._by_ident[ident] = task
        self._park(task)        # born parked; first go runs the body
        try:
            task.fn()
        except BaseException as e:      # noqa: BLE001 - reraised in run()
            task.exc = e
        finally:
            task.finished = True
            self._by_ident.pop(ident, None)
            task.parked.set()

    def _release_stragglers(self):
        """Failure-path cleanup: un-park unfinished tasks with the
        abort flag set, so each raises :class:`_SchedAbort` at its
        park point and unwinds — releasing whatever raw locks its
        ``with`` frames hold, which in turn un-wedges its peers. The
        join timeout is only a backstop for a task blocked outside the
        harness (real blocking on an unaudited primitive — daemon
        threads, so the process still exits)."""
        self._aborting = True
        for t in self.tasks:
            if not t.finished:
                t.go.set()
        for t in self.tasks:
            if t.thread is not None:
                t.thread.join(timeout=1.0)


class SchedQueue(queue.Queue):
    """``queue.Queue`` whose blocking get/put are sched-aware yield
    points on managed threads (real semantics everywhere else). Timed
    operations expire virtually: if the queue cannot satisfy them at
    their schedule point, Empty/Full raises immediately."""

    def get(self, block: bool = True, timeout: Optional[float] = None):
        s = _threads.scheduler()
        if s is None or not s.manages_current_thread():
            return super().get(block, timeout)
        task = s._current()
        s._park(task)           # pre-op schedule point
        while True:
            try:
                return super().get(False)
            except queue.Empty:
                if not block or timeout is not None:
                    raise
                s._park(task, blocked=("queue", self, "get"))

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        s = _threads.scheduler()
        if s is None or not s.manages_current_thread():
            return super().put(item, block, timeout)
        task = s._current()
        s._park(task)
        while True:
            try:
                return super().put(item, False)
            except queue.Full:
                if not block or timeout is not None:
                    raise
                s._park(task, blocked=("queue", self, "put"))


def explore(build: Callable[["VirtualScheduler"], Optional[Callable]],
            seeds: int = 64, base_seed: int = 0,
            name: str = "sched") -> int:
    """Sweep ``seeds`` interleavings: ``build(sched)`` spawns the tasks
    for one fresh scheduler and may return a post-run check callable
    (called with the completed scheduler). Failures name the seed and
    trace. Returns the number of schedules run."""
    for i in range(seeds):
        s = VirtualScheduler(seed=base_seed + i, name=f"{name}-{i}")
        check = build(s)
        s.run()
        if check is not None:
            check(s)
    return seeds
