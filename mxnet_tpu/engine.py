"""Execution engine facade.

The reference schedules every op through a dependency engine with versioned
variables (reference: include/mxnet/engine.h:117-318, src/engine/threaded_engine.h).
On TPU, XLA/PjRt dispatch is already asynchronous and ordered per-buffer, so
the engine's dependency tracking is absorbed by the runtime. What survives is
the *semantic* surface the reference exposes and tests
(tests/python/unittest/test_engine.py):

- engine selection (``MXNET_ENGINE_TYPE``): ``ThreadedEnginePerDevice`` (the
  async default — ops return immediately, results materialize later) vs
  ``NaiveEngine`` (synchronous oracle — every op blocks until complete; the
  race-free debugging mode, reference src/engine/naive_engine.cc:51).
- ``wait_for_all`` / per-array ``wait_to_read`` sync points where async
  exceptions surface (reference src/engine/threaded_engine.cc:422-436).
- op bulking knobs (``set_bulk_size``) — a no-op here because XLA fuses
  compiled programs; kept for API parity.
"""
from __future__ import annotations

import contextlib
import threading

import jax

from .base import get_env

__all__ = ["Engine", "get", "set_bulk_size", "bulk"]


class Engine:
    """Process-global engine facade (reference Engine::Get singleton)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self, kind: str):
        self.kind = kind
        self._bulk_size = 0

    @property
    def is_naive(self) -> bool:
        return self.kind == "NaiveEngine"

    def maybe_sync(self, arrays):
        """NaiveEngine blocks after every op — the synchronous oracle mode."""
        if self.is_naive:
            for a in arrays:
                jax.block_until_ready(a)

    def wait_for_all(self):
        """Block until all pending async work completes; raises deferred
        errors (reference Engine::WaitForAll; rethrow contract
        threaded_engine.cc:422-436). Deferred computation errors MUST
        propagate from here — only the absence of the barrier API itself is
        tolerated, never an error it reports."""
        barrier = getattr(jax, "effects_barrier", None)
        if barrier is not None:
            barrier()
        # Sync all locally-addressable devices; PjRt surfaces async errors
        # here (remote workers sync their own — reference WaitForAll is
        # per-process too).
        for d in jax.local_devices():
            sync = getattr(d, "synchronize_all_activity", None)
            if sync is None:
                break
            sync()

    def set_bulk_size(self, size: int) -> int:
        """Reference ThreadedEngine::set_bulk_size (threaded_engine.h:414).
        XLA fusion makes bulking implicit; we retain the knob."""
        old, self._bulk_size = self._bulk_size, int(size)
        return old

    @property
    def bulk_size(self) -> int:
        return self._bulk_size


_host_engine = None
_host_lock = threading.Lock()


def host():
    """The native C++ host-task engine (src/native/engine.cc) — versioned-
    variable dependency scheduling for host work (IO, decode, checkpoint
    writes), the part of the reference's ThreadedEngine that XLA does NOT
    absorb. Returns None when the native lib is unavailable."""
    global _host_engine
    if _host_engine is None:
        with _host_lock:
            if _host_engine is None:
                from . import _native
                if _native.available():
                    n = int(get_env("MXNET_CPU_WORKER_NTHREADS", "0"))
                    _host_engine = _native.NativeEngine(num_threads=n)
    return _host_engine


def get() -> Engine:
    if Engine._instance is None:
        with Engine._lock:
            if Engine._instance is None:
                kind = get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
                if kind not in ("NaiveEngine", "ThreadedEngine",
                                "ThreadedEnginePerDevice", "ThreadedEnginePooled"):
                    kind = "ThreadedEnginePerDevice"
                Engine._instance = Engine(kind)
    return Engine._instance


def set_bulk_size(size: int) -> int:
    return get().set_bulk_size(size)


@contextlib.contextmanager
def bulk(size: int):
    """Reference ``mx.engine.bulk`` context manager (python/mxnet/engine.py)."""
    old = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(old)
