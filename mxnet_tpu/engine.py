"""Execution engine facade.

The reference schedules every op through a dependency engine with versioned
variables (reference: include/mxnet/engine.h:117-318, src/engine/threaded_engine.h).
On TPU, XLA/PjRt dispatch is already asynchronous and ordered per-buffer, so
the engine's dependency tracking is absorbed by the runtime. What survives is
the *semantic* surface the reference exposes and tests
(tests/python/unittest/test_engine.py):

- engine selection (``MXNET_ENGINE_TYPE``): ``ThreadedEnginePerDevice`` (the
  async default — ops return immediately, results materialize later) vs
  ``NaiveEngine`` (synchronous oracle — every op blocks until complete; the
  race-free debugging mode, reference src/engine/naive_engine.cc:51).
- ``wait_for_all`` / per-array ``wait_to_read`` sync points where async
  exceptions surface (reference src/engine/threaded_engine.cc:422-436).
- op bulking knobs (``set_bulk_size``) — a no-op here because XLA fuses
  compiled programs; kept for API parity.
"""
from __future__ import annotations

import contextlib
import time
import weakref
from collections import deque
from typing import Any, Callable, Optional

import jax

from .analysis.threads import mx_lock
from .base import MXNetError, get_env
from .testing.faults import fault_point

# telemetry is imported lazily (the package initializes subsystems in
# dependency order) and cached; the registry half is always-on, the
# span/watchdog half gates itself on MXNET_TELEMETRY
_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from . import telemetry as _t
        _TELEM = _t
    return _TELEM


# elastic device-loss detection (elastic/detect.py), lazily reached the
# same way — it classifies failures escaping the retire seam
_EDET = None


def _edetect():
    global _EDET
    if _EDET is None:
        from .elastic import detect as _d
        _EDET = _d
    return _EDET

__all__ = ["Engine", "get", "set_bulk_size", "bulk", "DispatchWindow",
           "inflight_steps"]


class Engine:
    """Process-global engine facade (reference Engine::Get singleton)."""

    _instance = None
    _lock = mx_lock("engine.singleton")

    def __init__(self, kind: str):
        self.kind = kind
        self._bulk_size = 0

    @property
    def is_naive(self) -> bool:
        return self.kind == "NaiveEngine"

    def maybe_sync(self, arrays):
        """NaiveEngine blocks after every op — the synchronous oracle mode."""
        if self.is_naive:
            for a in arrays:
                jax.block_until_ready(a)

    def wait_for_all(self):
        """Block until all pending async work completes; raises deferred
        errors (reference Engine::WaitForAll; rethrow contract
        threaded_engine.cc:422-436). Deferred computation errors MUST
        propagate from here — only the absence of the barrier API itself is
        tolerated, never an error it reports."""
        # drain live dispatch windows first: their retire path attributes
        # an async failure to the STEP that faulted, which this barrier
        # alone cannot do
        for w in list(_live_windows):
            w.drain()
        barrier = getattr(jax, "effects_barrier", None)
        if barrier is not None:
            barrier()
        # Sync all locally-addressable devices; PjRt surfaces async errors
        # here (remote workers sync their own — reference WaitForAll is
        # per-process too).
        for d in jax.local_devices():
            sync = getattr(d, "synchronize_all_activity", None)
            if sync is None:
                break
            sync()

    def set_bulk_size(self, size: int) -> int:
        """Reference ThreadedEngine::set_bulk_size (threaded_engine.h:414).
        XLA fusion makes bulking implicit; we retain the knob."""
        old, self._bulk_size = self._bulk_size, int(size)
        return old

    @property
    def bulk_size(self) -> int:
        return self._bulk_size


#: live DispatchWindows, drained by Engine.wait_for_all (mx.nd.waitall)
_live_windows: "weakref.WeakSet" = weakref.WeakSet()


def inflight_steps(default: int = 2) -> int:
    """The bounded dispatch-window size: how many train-step futures
    the host may keep outstanding before it blocks on the oldest.
    Resolved autotune override > ``MXNET_INFLIGHT_STEPS`` > ``default``
    (the ``engine.inflight_steps`` tunable — tuning/space.py).
    ``NaiveEngine`` forces 0 — every step retires synchronously, the
    race-free oracle mode."""
    if get().is_naive:
        return 0
    from .tuning import space as _tspace
    found, v = _tspace.get_override("engine.inflight_steps")
    if not found:
        v = get_env("MXNET_INFLIGHT_STEPS", str(default))
    try:
        return max(0, int(v))
    except (TypeError, ValueError):
        return default


def _register_tunables():
    """The window-depth tunable, declared next to the constant it makes
    sweepable (docs/PERF_NOTES.md \"Autotuner\"). Window depth never
    changes numerics — losses are bit-exact at any W (pinned since
    PR 5) — only how much host dispatch overlap the device gets."""
    from .tuning.space import Tunable, register
    register(Tunable(
        "engine.inflight_steps", default=2, grid=(0, 1, 2, 3, 4, 6, 8),
        env="MXNET_INFLIGHT_STEPS", parse=int,
        valid=lambda v, _c: int(v) >= 0,
        seam="engine.inflight_steps() -> DispatchWindow max_inflight",
        scope="train",
        doc="async step futures outstanding before the host blocks on "
            "the oldest"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break engine
    import logging
    logging.getLogger("mxnet_tpu.tuning").debug(
        "engine tunable registration failed", exc_info=True)


class DispatchWindow:
    """Bounded in-flight async dispatch — ``Engine::PushAsync`` /
    ``WaitForVar`` semantics on PjRt.

    JAX arrays are already futures: a compiled step RETURNS immediately
    while the device works. What the reference engine adds — and this
    class reproduces — is the *bounded* part: ``push()`` records each
    step's async result, and only when more than ``max_inflight`` results
    are outstanding does the host block, on the OLDEST one (FIFO, the
    WaitForVar of step N-k). That keeps the host a fixed number of steps
    ahead of the device instead of either running unboundedly ahead or
    (the pre-engine behavior) syncing every step.

    Error contract (reference threaded_engine.cc:422-436): an async
    failure surfaces at the retire of the step that faulted — wrapped in
    an :class:`MXNetError` naming that step's tag — never silently at a
    later sync point with an unrelated traceback.

    The retire wait is the ONE blessed host sync of the pipelined hot
    loop: it runs under ``analysis.guard.allow_transfers`` and is counted
    separately (``window_retire``) from the unblessed NDArray syncs the
    transfer guard flags.
    """

    def __init__(self, max_inflight: Optional[int] = None,
                 sync_fn: Optional[Callable[[Any], Any]] = None,
                 what: str = "train step"):
        self.max_inflight = inflight_steps() if max_inflight is None \
            else max(0, int(max_inflight))
        self._sync = sync_fn if sync_fn is not None \
            else jax.block_until_ready
        self._what = what
        self._pending: "deque[tuple]" = deque()
        # pushes/retires run on the dispatching thread, but abandon()
        # arrives from recovery paths (elastic supervisor, fleet
        # failover) — _pending and stats mutations are guarded; the
        # blocking sync itself stays OUTSIDE the critical section so
        # an abandon never waits behind a dead device
        self._mu = mx_lock("engine.window")
        self.stats = {"pushes": 0, "retires": 0, "errors": 0,
                      "max_pending": 0}
        self._last_retire_t: Optional[float] = None
        t = _telemetry()
        reg = t.registry()
        self._m_pushes = reg.counter(t.names.WINDOW_PUSHES)
        self._m_retires = reg.counter(t.names.WINDOW_RETIRES)
        self._m_errors = reg.counter(t.names.WINDOW_ERRORS)
        self._m_occupancy = reg.gauge(t.names.WINDOW_OCCUPANCY)
        self._m_capacity = reg.gauge(t.names.WINDOW_CAPACITY)
        self._m_capacity.set(self.max_inflight)
        _live_windows.add(self)

    def __len__(self) -> int:
        return len(self._pending)

    def push(self, payload, tag=None, aux=None):
        """Record one dispatched async result; returns immediately unless
        the window is over capacity, in which case the OLDEST entry
        retires (blocks until that step completed). ``aux`` is an
        optional numerics record (``telemetry.StepNumerics``) riding
        alongside the payload: its on-device statistics are read at
        this entry's retire — inside the same blessed sync, after the
        step's program has completed — so numerics stay sync-free."""
        st = self.stats
        with self._mu:
            st["pushes"] += 1
            self._pending.append((tag, payload, aux, time.perf_counter()))
            if len(self._pending) > st["max_pending"]:
                st["max_pending"] = len(self._pending)
            depth = len(self._pending)
        self._m_pushes.inc()
        # re-assert per push: gauges survive telemetry.reset() zeroing
        self._m_capacity.set(self.max_inflight)
        self._m_occupancy.set(depth)
        while len(self._pending) > self.max_inflight:
            self._retire_oldest()

    def _retire_oldest(self):
        from .analysis import guard as _tguard
        with self._mu:
            if not self._pending:
                return      # abandoned concurrently by a recovery path
            tag, payload, aux, t_push = self._pending.popleft()
            depth = len(self._pending)
        self._m_occupancy.set(depth)
        _tguard.count_sync("window_retire")
        # chaos-harness seam: a revoked device surfaces exactly here in
        # a pipelined run — at the blocking wait on an in-flight step
        fault_point("window.retire", "before")
        t_wait = time.perf_counter()
        with _tguard.allow_transfers("dispatch-window retire"):
            try:
                self._sync(payload)
            except MXNetError as e:
                with self._mu:
                    self.stats["errors"] += 1
                self._m_errors.inc()
                _telemetry().memory.maybe_record_oom(
                    e, "dispatch-window retire", step=tag)
                _edetect().maybe_record_device_lost(
                    e, "dispatch-window retire", step=tag)
                raise
            except Exception as e:
                with self._mu:
                    self.stats["errors"] += 1
                self._m_errors.inc()
                # a deferred RESOURCE_EXHAUSTED surfaces HERE, steps
                # after the allocation that failed — write the ranked
                # post-mortem before wrapping (telemetry/memory.py);
                # a deferred device loss likewise gets its device_lost
                # anomaly (elastic/detect.py) before the wrap
                _telemetry().memory.maybe_record_oom(
                    e, "dispatch-window retire", step=tag)
                _edetect().maybe_record_device_lost(
                    e, "dispatch-window retire", step=tag)
                raise MXNetError(
                    f"async {self._what} "
                    f"{tag if tag is not None else '<untagged>'} failed "
                    f"(deferred error surfaced at its in-flight-window "
                    f"retire): {type(e).__name__}: {e}") from e
            with self._mu:
                self.stats["retires"] += 1
            self._m_retires.inc()
            # still inside the blessed retire region: the watchdog's
            # NaN peek at the (already completed) payload is the one
            # designed device->host read telemetry adds
            self._observe_retire(tag, payload, aux, t_push, t_wait)
        fault_point("window.retire", "after")

    def _observe_retire(self, tag, payload, aux, t_push, t_wait):
        """Step-timeline spans + watchdog feed for one retire — gated on
        MXNET_TELEMETRY / an active profiler; must never kill a run.
        The numerics aux (when the step was compiled with numerics
        instrumentation) is consumed FIRST and regardless of the
        telemetry gate — MXNET_NUMERICS is its own opt-in."""
        t = _telemetry()
        try:
            if aux is not None:
                t.numerics.monitor().observe_retire(tag, aux)
            if not t.active():
                self._last_retire_t = None
                return
            t_done = time.perf_counter()
            tl = t.timeline()
            tl.record("window", t_push, t_done, step=tag)
            tl.record("retire", t_wait, t_done, step=tag)
            dt = None if self._last_retire_t is None \
                else t_done - self._last_retire_t
            self._last_retire_t = t_done
            if t.enabled():
                t.watchdog().observe_retire(tag, payload=payload, dt=dt)
                # memory-budget headroom check, piggybacked on the same
                # blessed retire (no sync of its own; no-op unless
                # MXNET_MEMORY_BUDGET is set)
                t.memory.maybe_check_budget(step=tag)
        except Exception:            # pragma: no cover - defensive
            import logging
            logging.getLogger("mxnet_tpu.telemetry").warning(
                "window retire telemetry failed", exc_info=True)

    def drain(self):
        """Retire every outstanding entry (WaitForVar on all of them);
        deferred errors surface here attributed to their step."""
        while self._pending:
            self._retire_oldest()

    def abandon(self) -> list:
        """Discard every in-flight entry WITHOUT syncing — the recovery
        path after a device loss, where waiting on work dispatched to a
        dead device would only raise again. Returns the discarded tags
        (the steps whose results are gone; the checkpoint is the source
        of truth for them)."""
        with self._mu:
            tags = [t for t, _p, _a, _ts in self._pending]
            self._pending.clear()
            self.stats["abandoned"] = self.stats.get("abandoned", 0) \
                + len(tags)
        self._m_occupancy.set(0)
        return tags

    def drain_partial(self):
        """Recovery-drain: retire entries that still complete (in FIFO
        order — work the device finished before it was lost), then
        DISCARD everything after the first failure. Returns
        ``(retired, discarded_tags)``. The first failure is logged, not
        raised — the caller already holds the failure that started the
        recovery."""
        retired = 0
        while self._pending:
            try:
                self._retire_oldest()
                retired += 1
            except Exception as e:
                import logging
                logging.getLogger("mxnet_tpu.engine").warning(
                    "recovery drain: retire failed (%s: %s); discarding "
                    "%d in-flight step(s)", type(e).__name__, e,
                    len(self._pending))
                return retired, self.abandon()
        return retired, []


_host_engine = None
_host_lock = mx_lock("engine.host")


def host():
    """The native C++ host-task engine (src/native/engine.cc) — versioned-
    variable dependency scheduling for host work (IO, decode, checkpoint
    writes), the part of the reference's ThreadedEngine that XLA does NOT
    absorb. Returns None when the native lib is unavailable."""
    global _host_engine
    if _host_engine is None:
        with _host_lock:
            if _host_engine is None:
                from . import _native
                if _native.available():
                    n = int(get_env("MXNET_CPU_WORKER_NTHREADS", "0"))
                    _host_engine = _native.NativeEngine(num_threads=n)
    return _host_engine


def get() -> Engine:
    if Engine._instance is None:
        with Engine._lock:
            if Engine._instance is None:
                kind = get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
                if kind not in ("NaiveEngine", "ThreadedEngine",
                                "ThreadedEnginePerDevice", "ThreadedEnginePooled"):
                    kind = "ThreadedEnginePerDevice"
                Engine._instance = Engine(kind)
    return Engine._instance


def set_bulk_size(size: int) -> int:
    return get().set_bulk_size(size)


@contextlib.contextmanager
def bulk(size: int):
    """Reference ``mx.engine.bulk`` context manager (python/mxnet/engine.py)."""
    old = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(old)
