"""Runtime kernel compilation (reference: python/mxnet/rtc.py — CudaModule
compiling CUDA C source strings through NVRTC at runtime, src/common/rtc.cc).

TPU-native redesign: the runtime-compiled kernel language on TPU is
**Pallas**. ``PallasModule`` takes Python source defining Pallas kernels (or
an already-imported callable) and exposes them as framework ops with the
same get_kernel/launch flow the reference had. Compilation is XLA's job at
first call; caching is per-shape via jit.

The reference signature kept for parity::

    mod = mx.rtc.PallasModule(source)          # source defines kernel fns
    k = mod.get_kernel("my_kernel")            # by function name
    y = k.launch(x_ndarray)                    # runs on the TPU
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

from .base import MXNetError
from .ops.registry import invoke_raw

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasKernel:
    """A launchable kernel: wraps a jax-traceable callable (typically a
    ``pl.pallas_call`` wrapper) as a framework op."""

    def __init__(self, name: str, fn: Callable, num_outputs: int = 1):
        self.name = name
        self._fn = fn
        self._num_outputs = num_outputs

    def launch(self, *inputs, **attrs):
        fn = self._fn
        if attrs:
            import functools
            fn = functools.partial(fn, **attrs)
        return invoke_raw(f"rtc_{self.name}", fn, list(inputs),
                          n_outputs=self._num_outputs)

    __call__ = launch


class PallasModule:
    """Compile Python/Pallas source at runtime (reference CudaModule,
    rtc.py:41). ``source`` is Python code; every top-level callable not
    starting with '_' becomes a kernel. jax/jnp/pallas are pre-imported
    into the source's namespace."""

    def __init__(self, source: str, exports: Optional[Sequence[str]] = None):
        import jax
        import jax.numpy as jnp
        namespace: Dict = {"jax": jax, "jnp": jnp}
        try:
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu
            namespace["pl"] = pl
            namespace["pltpu"] = pltpu
        except ImportError:
            pass
        pre = set(namespace)
        try:
            exec(compile(source, "<rtc>", "exec"), namespace)
        except SyntaxError as e:
            raise MXNetError(f"rtc source failed to compile: {e}") from e
        import inspect
        self._kernels: Dict[str, PallasKernel] = {}
        names = exports if exports is not None else [
            k for k, v in namespace.items()
            if k not in pre and not k.startswith("_") and
            inspect.isfunction(v) and
            getattr(v, "__code__", None) is not None and
            v.__code__.co_filename == "<rtc>"]  # defined in the source,
        # not merely imported by it
        for name in names:
            if name not in namespace or not callable(namespace[name]):
                raise MXNetError(f"rtc source does not define {name!r}")
            self._kernels[name] = PallasKernel(name, namespace[name])

    def get_kernel(self, name: str, signature: Optional[str] = None
                   ) -> PallasKernel:
        """By-name lookup (the reference's signature arg described CUDA
        C types; shapes/dtypes are traced here, so it is accepted and
        ignored)."""
        if name not in self._kernels:
            raise MXNetError(
                f"kernel {name!r} not found; have {sorted(self._kernels)}")
        return self._kernels[name]


class CudaModule:
    """Reference API name. CUDA source cannot run on TPU — this build's
    runtime kernel path is PallasModule (same get_kernel/launch flow)."""

    def __init__(self, *a, **kw):
        raise MXNetError("CudaModule is CUDA-only; use mx.rtc.PallasModule "
                         "(Pallas source) on the TPU build")
