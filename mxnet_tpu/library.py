"""Runtime-loadable out-of-tree operator libraries.

Reference analog: ``MXLoadLib`` + the header-only C ABI of
``include/mxnet/lib_api.h`` (CustomOp :903, REGISTER_OP :1157), which lets
users ship compiled operators in a standalone shared library loaded with
dlopen — no framework rebuild.

TPU-native re-design: the C contract is a minimal host-side kernel ABI
(float32 buffers + shapes); each loaded op registers into the normal op
registry and executes through ``jax.pure_callback``, so it works eagerly
AND inside jit/hybridized computations (the callback runs on host while
XLA treats it as an opaque custom call — the role the reference's
CustomOperator thread pool played, custom-inl.h:103). Device-side custom
kernels belong in Pallas (``mx.rtc.PallasModule``); this path is for host
ops (IO, CPU-only third-party code).

Required exports (C, extern "C"):

    int  mxt_lib_num_ops(void);
    const char* mxt_lib_op_name(int op);
    // fill out_shape/out_ndim from input shapes; return 0 on success
    int  mxt_lib_op_infer_shape(int op, const long* const* in_shapes,
                                const int* in_ndims, int n_in,
                                long* out_shape, int* out_ndim);
    // float32 kernel; return 0 on success
    int  mxt_lib_op_forward(int op, const float* const* ins,
                            const long* const* in_shapes,
                            const int* in_ndims, int n_in,
                            float* out, const long* out_shape, int out_ndim);

Example library + build line: tests/test_library.py.
"""
from __future__ import annotations

import ctypes
from typing import List

import numpy as onp

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["load"]

_MAX_NDIM = 8

_LOADED = {}


class _LibOp:
    def __init__(self, lib, idx: int, name: str):
        self._lib = lib
        self._idx = idx
        self.name = name

    def infer_shape(self, in_shapes) -> tuple:
        n = len(in_shapes)
        shape_arrs = [(ctypes.c_long * len(s))(*s) for s in in_shapes]
        shapes = (ctypes.POINTER(ctypes.c_long) * n)(
            *[ctypes.cast(a, ctypes.POINTER(ctypes.c_long))
              for a in shape_arrs])
        ndims = (ctypes.c_int * n)(*[len(s) for s in in_shapes])
        out_shape = (ctypes.c_long * _MAX_NDIM)()
        out_ndim = ctypes.c_int(0)
        rc = self._lib.mxt_lib_op_infer_shape(
            self._idx, shapes, ndims, n, out_shape,
            ctypes.byref(out_ndim))
        if rc != 0:
            raise MXNetError(
                f"library op {self.name!r}: infer_shape failed (rc={rc})")
        return tuple(out_shape[i] for i in range(out_ndim.value))

    def forward_host(self, *arrays: onp.ndarray) -> onp.ndarray:
        arrays = [onp.ascontiguousarray(a, dtype=onp.float32)
                  for a in arrays]
        in_shapes = [a.shape for a in arrays]
        out_shape = self.infer_shape(in_shapes)
        out = onp.zeros(out_shape, dtype=onp.float32)
        n = len(arrays)
        shape_arrs = [(ctypes.c_long * len(s))(*s) for s in in_shapes]
        shapes = (ctypes.POINTER(ctypes.c_long) * n)(
            *[ctypes.cast(a, ctypes.POINTER(ctypes.c_long))
              for a in shape_arrs])
        ndims = (ctypes.c_int * n)(*[len(s) for s in in_shapes])
        ins = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        oshape = (ctypes.c_long * len(out_shape))(*out_shape)
        rc = self._lib.mxt_lib_op_forward(
            self._idx, ins, shapes, ndims, n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            oshape, len(out_shape))
        if rc != 0:
            raise MXNetError(
                f"library op {self.name!r}: forward failed (rc={rc})")
        return out

    def kernel(self, *xs):
        """JAX-facing kernel. Eager calls run the C forward on host
        directly (works on every platform, including PjRt plugins without
        host-callback support). Inside a trace the op lowers to
        ``jax.pure_callback`` — an opaque host custom-call — which requires
        a callback-capable platform (CPU/TPU; some tunneled PjRt plugins
        lack send/recv callbacks, in which case keep library ops outside
        hybridized blocks)."""
        if not any(isinstance(x, jax.core.Tracer) for x in xs):
            return jnp.asarray(self.forward_host(
                *[onp.asarray(x) for x in xs]))
        out_shape = self.infer_shape([tuple(x.shape) for x in xs])
        cb = lambda *h: self.forward_host(*h)  # noqa: E731
        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct(out_shape, jnp.float32),
            *[jnp.asarray(x, jnp.float32) for x in xs])


def load(path: str, verbose: bool = True) -> List[str]:
    """Load a compiled operator library; returns the op names registered.

    Reference MXLoadLib (python/mxnet/library.py): ops become callable as
    ``mx.nd.<name>(...)`` and through the op registry (``invoke``)."""
    if path in _LOADED:
        return _LOADED[path]
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot dlopen {path!r}: {e}") from e
    for sym in ("mxt_lib_num_ops", "mxt_lib_op_name",
                "mxt_lib_op_infer_shape", "mxt_lib_op_forward"):
        if not hasattr(lib, sym):
            raise MXNetError(
                f"{path!r} is not an op library: missing symbol {sym}")
    lib.mxt_lib_op_name.restype = ctypes.c_char_p
    names = []
    from . import ndarray as nd_mod
    for i in range(int(lib.mxt_lib_num_ops())):
        name = lib.mxt_lib_op_name(i).decode()
        op = _LibOp(lib, i, name)
        _registry.register(name, differentiable=False)(op.kernel)

        def make_wrapper(o):
            def wrapper(*inputs, **_ignored):
                arrs = [x if isinstance(x, nd_mod.NDArray)
                        else nd_mod.array(x) for x in inputs]
                return _registry.invoke(o.name, *arrs)
            wrapper.__name__ = o.name
            wrapper.__doc__ = f"out-of-tree library op from {path}"
            return wrapper

        setattr(nd_mod, name, make_wrapper(op))
        names.append(name)
    if verbose:
        print(f"loaded library {path}: ops {names}")
    _LOADED[path] = names
    return names
