"""Legacy data-iterator API (reference: python/mxnet/io/ + src/io/).

``DataIter`` subclasses yield ``DataBatch`` objects with ``provide_data``/
``provide_label`` descriptors — the pre-Gluon input pipeline the reference
keeps for compatibility (io.py DataIter/NDArrayIter/CSVIter and the C++
MXDataIter iterators registered via MXNET_REGISTER_IO_ITER).

TPU-native notes: ``ImageRecordIter`` reads dmlc RecordIO through the
native C++ prefetcher thread (src/native/recordio.cc — the role of the
reference's iter_image_recordio_2.cc decode/prefetch pipeline), decoding
and augmenting in Python via mx.image.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, CSVIter,  # noqa: F401
                 ResizeIter, PrefetchingIter, ImageRecordIter, MXDataIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "MXDataIter"]
