"""DataIter implementations (reference: python/mxnet/io/io.py).

Cites: DataBatch/DataDesc (io.py:81,36), DataIter (io.py:202), NDArrayIter
(utils.py/io.py:683), CSVIter + ImageRecordIter (C++ iterators surfaced as
MXDataIter, src/io/iter_csv.cc / iter_image_recordio_2.cc:887).
"""
from __future__ import annotations

import collections
import threading
from typing import List, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as nd_array
from .. import recordio as rio

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "MXDataIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Data descriptor (reference io.py:36); dtype/layout as attributes."""

    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        self = super().__new__(cls, name, tuple(shape))
        self.dtype = dtype
        self.layout = layout
        return self


class DataBatch:
    """One batch: data/label lists + pad/index bookkeeping (io.py:81)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference io.py:202): next/reset/iter protocol."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        raise NotImplementedError

    def __next__(self):
        return self.next()

    @property
    def provide_data(self) -> List[DataDesc]:
        return []

    @property
    def provide_label(self) -> List[DataDesc]:
        return []


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class NDArrayIter(DataIter):
    """Batches over in-memory arrays with pad/discard/roll_over last-batch
    handling and optional shuffle (reference io.py:683 NDArrayIter)."""

    def __init__(self, data, label=None, batch_size: int = 1,
                 shuffle: bool = False, last_batch_handle: str = "pad",
                 data_name: str = "data", label_name: str = "softmax_label"):
        super().__init__(batch_size)
        self.data = self._canonize(data, data_name)
        self.label = self._canonize(label, label_name) if label is not None \
            else []
        self.shuffle = shuffle
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError("last_batch_handle must be pad/discard/roll_over")
        self.last_batch_handle = last_batch_handle
        self.num_data = self.data[0][1].shape[0]
        self._idx = onp.arange(self.num_data)
        self.cursor = 0
        self.reset()

    @staticmethod
    def _canonize(data, default_name):
        if data is None:
            return []
        if isinstance(data, (onp.ndarray, NDArray)):
            return [(default_name, _to_numpy(data))]
        if isinstance(data, dict):
            return [(k, _to_numpy(v)) for k, v in sorted(data.items())]
        if isinstance(data, (list, tuple)):
            return [(f"{default_name}_{i}" if i else default_name,
                     _to_numpy(v)) for i, v in enumerate(data)]
        raise MXNetError(f"unsupported data type {type(data)}")

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:],
                         dtype=str(a.dtype)) for n, a in self.data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:],
                         dtype=str(a.dtype)) for n, a in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self._idx)
        self.cursor = 0

    def next(self) -> DataBatch:
        if self.cursor >= self.num_data:
            raise StopIteration
        end = self.cursor + self.batch_size
        pad = 0
        if end > self.num_data:
            if self.last_batch_handle == "discard":
                raise StopIteration
            pad = end - self.num_data
            if self.last_batch_handle == "roll_over":
                idx = onp.concatenate([self._idx[self.cursor:],
                                       self._idx[:pad]])
            else:  # pad: repeat from the front
                idx = onp.concatenate([self._idx[self.cursor:],
                                       self._idx[:pad]])
        else:
            idx = self._idx[self.cursor:end]
        self.cursor = end
        data = [nd_array(a[idx]) for _, a in self.data]
        label = [nd_array(a[idx]) for _, a in self.label]
        return DataBatch(data, label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class CSVIter(DataIter):
    """CSV reader (reference src/io/iter_csv.cc surfaced via MXDataIter):
    row-major float CSV; ``data_shape`` reshapes each row."""

    def __init__(self, data_csv: str, data_shape, batch_size: int,
                 label_csv: Optional[str] = None, label_shape=(1,),
                 round_batch: bool = True):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype="float32", ndmin=2)
        self._data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = onp.loadtxt(label_csv, delimiter=",", dtype="float32",
                                ndmin=2).reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            self._data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class ResizeIter(DataIter):
    """Truncates/extends an iterator to ``size`` batches (io.py ResizeIter)."""

    def __init__(self, data_iter: DataIter, size: int,
                 reset_internal: bool = True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """Background-thread prefetch wrapper (reference io.py PrefetchingIter /
    C++ iter_prefetcher.h): overlaps batch production with consumption."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth: int = 2):
        it = iters[0] if isinstance(iters, (list, tuple)) else iters
        super().__init__(it.batch_size)
        self.iter = it
        self._queue = collections.deque()
        self._sem = threading.Semaphore(0)
        self._space = threading.Semaphore(prefetch_depth)
        # bare on purpose: leaf iterator lock; never nests with audited locks
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        self._done = False
        self._thread = None
        self._start()

    def _start(self):
        self._done = False

        def loop():
            while True:
                self._space.acquire()
                try:
                    batch = self.iter.next()
                except StopIteration:
                    with self._lock:
                        self._queue.append(None)
                    self._sem.release()
                    return
                with self._lock:
                    self._queue.append(batch)
                self._sem.release()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def reset(self):
        if self._thread is not None:
            # drain current producer
            while self._thread.is_alive():
                self._space.release()
                self._thread.join(timeout=0.01)
        self._queue.clear()
        self._sem = threading.Semaphore(0)
        self._space = threading.Semaphore(2)
        self.iter.reset()
        self._start()

    def next(self):
        self._sem.acquire()
        with self._lock:
            batch = self._queue.popleft()
        self._space.release()
        if batch is None:
            raise StopIteration
        return batch


class ImageRecordIter(DataIter):
    """Image RecordIO iterator (reference ImageRecordIter,
    src/io/iter_image_recordio_2.cc:887): records are IRHeader-packed
    encoded images; reading is done by the native C++ prefetcher thread,
    decode + augment + batch in Python (mx.image)."""

    def __init__(self, path_imgrec: str, data_shape, batch_size: int,
                 label_width: int = 1, shuffle: bool = False,
                 rand_crop: bool = False, rand_mirror: bool = False,
                 mean_r: float = 0., mean_g: float = 0., mean_b: float = 0.,
                 std_r: float = 1., std_g: float = 1., std_b: float = 1.,
                 preprocess_threads: int = 4, prefetch_buffer: int = 64,
                 round_batch: bool = True, **kwargs):
        super().__init__(batch_size)
        self.path_imgrec = path_imgrec
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = onp.array([mean_r, mean_g, mean_b], "float32")
        self.std = onp.array([std_r, std_g, std_b], "float32")
        self.prefetch_buffer = prefetch_buffer
        self.round_batch = round_batch
        self._reader = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label",
                         (self.batch_size, self.label_width)
                         if self.label_width > 1 else (self.batch_size,))]

    def reset(self):
        if self._reader is not None:
            try:
                self._reader.close()
            except Exception:
                pass
        from .. import _native
        if _native.available():
            self._reader = _native.NativePrefetchReader(
                self.path_imgrec, capacity=self.prefetch_buffer)
            self._read = self._reader.read
        else:
            self._reader = rio.MXRecordIO(self.path_imgrec, "r")
            self._read = self._reader.read

    def _decode_one(self, rec: bytes):
        from .. import image as img_mod
        header, payload = rio.unpack(rec)
        c, h, w = self.data_shape
        img = img_mod.imdecode_or_raw(payload, self.data_shape)
        arr = img.astype("float32")  # HWC
        if arr.shape[0] != h or arr.shape[1] != w:
            arr = img_mod.imresize_np(arr, w, h)
        if self.rand_mirror and onp.random.rand() < 0.5:
            arr = arr[:, ::-1]
        arr = (arr - self.mean) / self.std
        label = header.label
        if isinstance(label, onp.ndarray):
            lab = label[:self.label_width]
        else:
            lab = onp.array([label], "float32")[:self.label_width]
        return arr.transpose(2, 0, 1), lab  # CHW

    def next(self) -> DataBatch:
        datas, labels = [], []
        while len(datas) < self.batch_size:
            rec = self._read()
            if rec is None:
                break
            d, l = self._decode_one(rec)
            datas.append(d)
            labels.append(l)
        if not datas:
            raise StopIteration
        pad = self.batch_size - len(datas)
        if pad and not self.round_batch:
            raise StopIteration
        while len(datas) < self.batch_size:  # pad by repeating
            datas.append(datas[-1])
            labels.append(labels[-1])
        data = nd_array(onp.stack(datas))
        lab = onp.stack(labels)
        if self.label_width == 1:
            lab = lab[:, 0]
        return DataBatch([data], [nd_array(lab)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


# reference exposes C++ iterators through MXDataIter; our native-backed
# iterators are constructed directly, so the alias points at the closest one
MXDataIter = ImageRecordIter
