"""Gradient compression with error feedback.

Reference analog: src/kvstore/gradient_compression.{h,cc,cu} — 2-bit
stochastic quantization with a residual buffer, applied before network
transfer. TPU-native: the quantize/dequantize pair is a pure jitted function
(XLA fuses it; a Pallas variant can replace it when profiling shows need),
applied before DCN allreduce where bandwidth is scarce; ICI is fast enough
that compression is off by default, matching the reference's opt-in design.
"""
from __future__ import annotations

from typing import Dict, Hashable, Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["GradientCompression"]


class GradientCompression:
    """type='2bit' (threshold) or 'fp16'/'bf16' casts
    (reference set_gradient_compression params)."""

    def __init__(self, type: str = "2bit", threshold: float = 0.5):  # noqa: A002
        if type not in ("2bit", "1bit", "fp16", "bf16"):
            raise MXNetError(f"unsupported compression type {type!r}")
        self.type = type
        self.threshold = float(threshold)
        self._residuals: Dict[Hashable, jax.Array] = {}
        self._fn = jax.jit(self._make_fn())

    def _make_fn(self):
        t = self.threshold
        kind = self.type

        def fn(g, residual):
            g = g + residual
            if kind == "2bit":
                q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0))
            elif kind == "1bit":
                q = jnp.where(g >= 0, t, -t)
            elif kind == "fp16":
                q = g.astype(jnp.float16).astype(g.dtype)
            else:
                q = g.astype(jnp.bfloat16).astype(g.dtype)
            return q, g - q  # (compressed value, new error residual)
        return fn

    def compress_decompress(self, grad: NDArray,
                            key: Optional[Hashable] = None) -> NDArray:
        """Round-trip compress (what the wire would carry) with error
        feedback accumulation.

        Residuals are keyed by the caller-supplied ``key`` — the kvstore
        parameter key plus replica index (reference keeps one residual per
        kvstore key per device, gradient_compression.h:38-121). Keying by
        buffer identity is unsound: ids are reused after GC, and the buffer
        changes every step."""
        if key is None:
            key = id(grad)  # legacy fallback for direct callers
        res = self._residuals.get(key)
        if res is None or res.shape != grad._data.shape:
            res = jnp.zeros_like(grad._data)
        q, new_res = self._fn(grad._data, res)
        self._residuals[key] = new_res
        return NDArray(q)
