"""Parallelism: device meshes, sharding helpers, collectives, compression.

Reference analog: src/kvstore/'s Comm/NCCL/ps-lite stack plus the manual
model-parallel placement story (SURVEY §2.3). TPU-native design: ONE
abstraction — a `jax.sharding.Mesh` with named axes — carries every
parallelism flavor (dp/tp/pp/sp/ep); annotate shardings, let XLA insert the
ICI/DCN collectives.
"""
from .mesh import (DeviceMesh, make_mesh, current_mesh, data_parallel_mesh,
                   shard_batch, replicate, shard_params, zero_shard_pad,
                   zero_shard_sharding, place_on_mesh)
from .compression import GradientCompression
from . import mesh, compression, dist, collectives, pipeline
from .collectives import (allreduce, allgather, reduce_scatter,
                          broadcast_axis, ppermute, shard_map)
from .pipeline import pipeline_apply, run_pipeline
