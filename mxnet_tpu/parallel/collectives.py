"""Collective operations over mesh axes.

Reference analog: the Comm reduce paths (src/kvstore/comm.h), NCCL
collectives (kvstore_nccl.h), and tree reduction (comm_tree.h). On TPU every
one of these is an XLA collective over a mesh axis: psum/all_gather/
reduce_scatter/ppermute riding ICI. These helpers wrap shard_map so
imperative code can call collectives on sharded NDArrays.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .mesh import DeviceMesh, current_mesh

__all__ = ["allreduce", "allgather", "reduce_scatter", "broadcast_axis",
           "ppermute", "reduce_scatter_bucketed", "allgather_bucketed"]


def _get_mesh(mesh):
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError("no active mesh; wrap in `with make_mesh(...)`")
    return mesh


def shard_map(fn, mesh, in_spec, out_spec):
    """Version-compat ``shard_map`` with value-based replication checks
    off (check_vma/check_rep: e.g. a tiled all_gather's output IS
    replicated over the axis but the varying-axis inference can't prove
    it; numerics are asserted in tests/test_parallel.py instead). Resolves
    ``jax.shard_map`` (new jax) or ``jax.experimental.shard_map`` (<=0.4.x)
    and whichever check kwarg that version spells. Accepts a DeviceMesh or
    a raw jax Mesh — the supported entry point for user/example code."""
    raw = mesh.mesh if isinstance(mesh, DeviceMesh) else mesh
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    for check_kwarg in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return impl(fn, mesh=raw, in_specs=in_spec,
                        out_specs=out_spec, **check_kwarg)
        except TypeError:  # this jax spells the check kwarg differently
            continue
    raise MXNetError("no usable shard_map in this jax version")


_shard_map = shard_map  # internal alias (pre-existing call sites)


def _on_mesh(x: NDArray, mesh: DeviceMesh, spec) -> jax.Array:
    """Place the operand on the mesh with the collective's input layout.
    Imperative callers usually hold single-device arrays (the reference's
    kvstore accepted plain NDArrays the same way); already-matching sharded
    arrays pass through without a copy."""
    from jax.sharding import NamedSharding
    return jax.device_put(x._data, NamedSharding(mesh.mesh, spec))


def allreduce(x: NDArray, axis: str = "dp",
              mesh: Optional[DeviceMesh] = None, op: str = "sum") -> NDArray:
    """psum over a mesh axis (the kvstore pushpull primitive)."""
    mesh = _get_mesh(mesh)

    def f(v):
        if op == "sum":
            return jax.lax.psum(v, axis)
        if op == "mean":
            return jax.lax.pmean(v, axis)
        if op == "max":
            return jax.lax.pmax(v, axis)
        raise MXNetError(f"unknown reduce op {op}")
    spec = _batch_spec(x, axis)
    out = _shard_map(f, mesh, (spec,), spec)(_on_mesh(x, mesh, spec))
    return NDArray(out)


def allgather(x: NDArray, axis: str = "dp",
              mesh: Optional[DeviceMesh] = None, tiled: bool = True) -> NDArray:
    mesh = _get_mesh(mesh)

    def f(v):
        return jax.lax.all_gather(v, axis, tiled=tiled)
    spec = _batch_spec(x, axis)
    out = _shard_map(f, mesh, (spec,), P())(_on_mesh(x, mesh, spec))
    return NDArray(out)


def reduce_scatter(x: NDArray, axis: str = "dp",
                   mesh: Optional[DeviceMesh] = None) -> NDArray:
    """psum_scatter over a mesh axis: each shard receives the reduced
    1/N tile of the leading dim — the first leg of the ZeRO-1 sharded
    weight update (reduce-scatter → shard-local update → all-gather,
    arXiv:2004.13336). A leading dim not divisible by the axis size is
    zero-padded before the scatter and sliced back after, so arbitrary
    parameter shapes ride the same collective."""
    mesh = _get_mesh(mesh)
    n = mesh.shape[axis]
    lead = int(x.shape[0]) if x.ndim >= 1 else 1
    if x.ndim == 0:
        raise MXNetError("reduce_scatter needs a >=1-d operand")
    pad = (-lead) % n
    data = x._data
    if pad:
        data = jnp.pad(data, ((0, pad),) + ((0, 0),) * (x.ndim - 1))

    def f(v):
        return jax.lax.psum_scatter(v, axis, tiled=True)
    out = _shard_map(f, mesh, (P(),),
                     _batch_spec_ndim(x.ndim, axis))(
                         _on_mesh(NDArray(data), mesh, P()))
    if pad:
        out = out[:lead]
    return NDArray(out)


def broadcast_axis(x: NDArray, axis: str = "dp",
                   mesh: Optional[DeviceMesh] = None, src: int = 0) -> NDArray:
    """Broadcast shard `src`'s value to all shards along the axis."""
    mesh = _get_mesh(mesh)
    n = mesh.shape[axis]

    def f(v):
        # psum of the src-masked value: every shard receives src's block
        # (ppermute can't fan out one source to many destinations)
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == src, v, jnp.zeros_like(v))
        return jax.lax.psum(masked, axis)
    spec = _batch_spec(x, axis)
    out = _shard_map(f, mesh, (spec,), spec)(_on_mesh(x, mesh, spec))
    return NDArray(out)


def ppermute(x: NDArray, perm, axis: str = "dp",
             mesh: Optional[DeviceMesh] = None) -> NDArray:
    mesh = _get_mesh(mesh)

    def f(v):
        return jax.lax.ppermute(v, axis, perm)
    spec = _batch_spec(x, axis)
    out = _shard_map(f, mesh, (spec,), spec)(_on_mesh(x, mesh, spec))
    return NDArray(out)


# ---------------------------------------------------------------------------
# bucketed flat-segment collectives (trace-level: jax arrays, usable
# inside jit — the ZeRO-1 fused step's communication bucketing rides
# these; gluon/fused_step.py)
# ---------------------------------------------------------------------------

def _bucket_rows(segs, num_shards: int):
    """Pad each flat segment to ``num_shards`` divisibility and view it
    as ``(num_shards, s_k)`` rows.  Returns ``(rows, cols)`` where
    ``cols[k]`` is the per-shard column count of segment ``k``."""
    rows, cols = [], []
    for g in segs:
        g = jnp.reshape(g, (-1,))
        n = int(g.shape[0])
        s = -(-n // num_shards)
        pad = s * num_shards - n
        if pad:
            g = jnp.pad(g, (0, pad))
        rows.append(g.reshape(num_shards, s))
        cols.append(s)
    return rows, cols


def reduce_scatter_bucketed(segs, num_shards: int, constrain=None):
    """One reduce-scatter per BUCKET instead of one per segment.

    ``segs`` is a list of flat gradient segments (arbitrary lengths;
    each is zero-padded to ``num_shards`` divisibility).  Every segment
    is viewed as ``(num_shards, s_k)`` and the views concatenate on the
    free axis into a single ``(num_shards, S)`` buffer, so ONE
    collective on the leading dim hands shard ``d`` exactly
    ``[seg_0[d*s_0:(d+1)*s_0], seg_1[...], ...]`` — per-segment shard
    extraction afterwards is a comm-free slice on the free axis.

    ``constrain`` maps the ``(num_shards, S)`` buffer to its sharded
    layout (e.g. ``lambda b: with_sharding_constraint(b,
    NamedSharding(mesh, P(axis, None)))``) and is where the collective
    actually materializes; ``None`` is the identity, which makes the
    routing itself unit-testable without a mesh.

    Returns a list of flat ``(num_shards * s_k,)`` padded segments in
    input order (values identical to padding + constraining each
    segment individually — the packing is pure routing).
    """
    rows, cols = _bucket_rows(segs, num_shards)
    buf = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
    if constrain is not None:
        buf = constrain(buf)
    outs, off = [], 0
    for s in cols:
        outs.append(buf[:, off:off + s].reshape(num_shards * s))
        off += s
    return outs


def allgather_bucketed(shards, num_shards: int, constrain=None,
                       orig_lens=None):
    """One all-gather per BUCKET: the inverse routing of
    :func:`reduce_scatter_bucketed`.

    ``shards`` is a list of flat sharded segments whose lengths are
    ``num_shards``-divisible (the reduce-scatter outputs, or the
    optimizer's new weights computed from them).  They concatenate into
    the same interleaved ``(num_shards, S)`` buffer, ``constrain``
    replicates it (the all-gather), and per-segment full values slice
    back out comm-free.  ``orig_lens`` (optional, per segment) strips
    the scatter padding; ``None`` keeps segments padded.

    Returns the list of flat replicated segments in input order.
    """
    rows = []
    for w in shards:
        w = jnp.reshape(w, (-1,))
        n = int(w.shape[0])
        if n % num_shards:
            raise MXNetError(
                "allgather_bucketed: segment length %d not divisible "
                "by num_shards=%d (pass reduce_scatter_bucketed "
                "outputs)" % (n, num_shards))
        rows.append(w.reshape(num_shards, n // num_shards))
    buf = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
    if constrain is not None:
        buf = constrain(buf)
    outs, off = [], 0
    for k, r in enumerate(rows):
        s = r.shape[1]
        full = buf[:, off:off + s].reshape(num_shards * s)
        if orig_lens is not None:
            full = full[:int(orig_lens[k])]
        outs.append(full)
        off += s
    return outs


def _batch_spec(x: NDArray, axis: str):
    return _batch_spec_ndim(x.ndim, axis)


def _batch_spec_ndim(ndim: int, axis: str):
    return P(axis, *([None] * (ndim - 1)))
