"""Pipeline parallelism: GPipe-style microbatch schedule over a 'pp' mesh
axis.

No reference analog (SURVEY §2.3: pipeline parallelism absent upstream —
the reference only had manual per-ctx layer placement with cross-device
copies, model_parallel_lstm.md). TPU-native design: each device along the
``pp`` axis owns ONE stage's weights; microbatches stream through the ring
with ``lax.ppermute`` hops, so stage s computes microbatch m at tick
t = s + m — the classic GPipe fill/drain schedule, expressed as a
``lax.scan`` inside ``shard_map`` (differentiable end-to-end: reverse-mode
through scan + ppermute gives the 1F1B-equivalent backward automatically).

Uniform activation shape across stages is required (the transformer/MLP
case); a stage is any ``fn(stage_params, x) -> y`` with y.shape == x.shape.

``double_buffer=True`` switches to a one-slot-delay schedule that holds
TWO ring carries: the hop launched at tick t is not consumed until tick
t+2, so the collective-permute of microbatch m's activations is in
flight while the stage computes microbatch m+1 — the permute latency
hides behind compute instead of sitting on the critical path between
ticks.  The price is a deeper fill/drain bubble (2·(pp-1) ticks instead
of pp-1); per-microbatch results are bit-identical either way, only the
schedule changes.  Default comes from ``MXNET_PIPELINE_DOUBLE_BUFFER``.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["pipeline_apply", "run_pipeline"]


def _double_buffer_default() -> bool:
    return os.environ.get("MXNET_PIPELINE_DOUBLE_BUFFER", "0").lower() in (
        "1", "true", "yes", "on")


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp",
                   double_buffer=None):
    """Run inside shard_map over ``axis_name``. ``stage_params`` are THIS
    device's stage weights; ``microbatches`` (M, mb, ...) the full
    replicated stream. Returns (M, mb, ...) outputs, replicated (last
    stage's results psum-broadcast). ``double_buffer`` selects the
    latency-hiding one-slot-delay hop schedule (None → the
    ``MXNET_PIPELINE_DOUBLE_BUFFER`` env default)."""
    if double_buffer is None:
        double_buffer = _double_buffer_default()
    pp = lax.psum(1, axis_name)  # axis size (lax.axis_size needs newer jax)
    idx = lax.axis_index(axis_name)
    m_count = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    # hop latency in ticks: 1 for the classic GPipe ring (a hop launched
    # at tick t is eaten at t+1, serializing permute after compute), 2
    # when double-buffered (the hop rides a second carry slot for one
    # extra tick, so it permutes WHILE tick t+1 computes)
    lat = 2 if double_buffer else 1

    def tick(carry_out, t):
        ready, inflight, outputs = carry_out
        # stage 0 ingests microbatch t (while it exists); later stages eat
        # the ring carry from their predecessor
        inp = jnp.where(idx == 0,
                        microbatches[jnp.clip(t, 0, m_count - 1)], ready)
        out = stage_fn(stage_params, inp)
        # the last stage emits microbatch j = t - lat*(pp-1) once the
        # pipe fills
        j = t - lat * (pp - 1)
        outputs = jnp.where((idx == pp - 1) & (j >= 0),
                            outputs.at[jnp.clip(j, 0, m_count - 1)].set(out),
                            outputs)
        hop = lax.ppermute(out, axis_name, perm)
        if double_buffer:
            # this tick's hop parks in the inflight slot; the PREVIOUS
            # tick's hop (already a full compute tick in flight) becomes
            # next tick's input
            return (inflight, hop, outputs), None
        return (hop, inflight, outputs), None

    def _varying(a):
        # the ring carry differs per device; mark the initial zeros as
        # pp-varying so scan's carry types line up (JAX VMA tracking).
        # jax versions without pcast/pvary have no VMA tracking (we run
        # shard_map with the replication check off) — identity is correct.
        for name, kw in (("pcast", {"to": "varying"}), ("pvary", {})):
            fn = getattr(lax, name, None)
            if fn is not None:
                try:
                    return fn(a, (axis_name,), **kw)
                except TypeError:
                    continue
        return a

    init = (_varying(jnp.zeros(mb_shape, microbatches.dtype)),
            _varying(jnp.zeros(mb_shape, microbatches.dtype)),
            _varying(jnp.zeros((m_count,) + mb_shape, microbatches.dtype)))
    (_, _, outputs), _ = lax.scan(tick, init,
                                  jnp.arange(m_count + lat * (pp - 1)))
    # broadcast the last stage's buffer to every device so callers can use
    # replicated out_specs
    return lax.psum(jnp.where(idx == pp - 1, outputs,
                              jnp.zeros_like(outputs)), axis_name)


def run_pipeline(stage_fn, stacked_params, x, num_microbatches, mesh,
                 axis_name="pp", double_buffer=None):
    """Convenience wrapper: shard ``stacked_params`` (leading dim = number
    of stages) over ``axis_name`` of ``mesh``, split batch ``x`` into
    ``num_microbatches``, run the pipeline, return (B, ...) outputs."""
    from jax.sharding import PartitionSpec as P
    pp = mesh.shape[axis_name]
    b = x.shape[0]
    if b % num_microbatches:
        raise MXNetError(
            f"batch {b} not divisible into {num_microbatches} microbatches")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != pp:
            raise MXNetError(
                f"stacked_params leading dim {leaf.shape[0]} != pipeline "
                f"size {pp} (one stage per '{axis_name}' device)")
    micro = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    def shard_fn(params_local, micro_all):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
        return pipeline_apply(stage_fn, params_local, micro_all, axis_name,
                              double_buffer=double_buffer)

    from .collectives import shard_map as _compat_shard_map
    out = _compat_shard_map(
        shard_fn, mesh,
        (P(axis_name), P()), P())(stacked_params, micro)
    return out.reshape(b, *out.shape[2:])


# ---------------------------------------------------------------------------
# sharding spec pack (analysis/sharding.py expect_spec)
# ---------------------------------------------------------------------------
# The GPipe schedule's contract, declared next to the implementation:
# microbatches hop the ring with lax.ppermute (>= 1 collective-permute
# on 'pp' — XLA fuses the scan body's hop into one op) and the last
# stage's outputs broadcast back with ONE psum (>= 1 all-reduce); the
# stage weights (leading dim 'pp'-sharded by run_pipeline) must live at
# ~1/pp per device.  An all-gather above the floor means a stage pulled
# another stage's weights or activations — the cross-stage
# materialization pipelining exists to avoid.
try:
    from ..analysis import sharding as _asharding

    PIPELINE_SPEC_PACK = _asharding.register_spec_pack(
        _asharding.SpecPack(
            name="pp-gpipe",
            description="GPipe microbatch pipeline (ppermute ring hops "
                        "+ one last-stage psum broadcast)",
            axes=("pp",),
            rules=(
                _asharding.CollectiveRule("collective_permute",
                                          axis="pp", min_count=1),
                _asharding.CollectiveRule("all_reduce", axis="pp",
                                          min_count=1),
            ),
            declared=(),
            state_axis="pp"))
except Exception:                        # pragma: no cover - defensive
    pass
