"""Multi-host distributed runtime.

Reference analog: ps-lite worker/server/scheduler roles launched by
tools/launch.py with DMLC_* env vars (SURVEY §2.3). TPU-native: one SPMD
program per host over a global mesh; `jax.distributed.initialize` replaces
the tracker, and DCN-spanning XLA collectives replace ZMQ push/pull. The
DMLC_* env names are honored so reference launch scripts keep working.
"""
from __future__ import annotations

import logging
import os
import random as _pyrandom
import time
from typing import Optional

import jax

from ..base import MXNetError, get_env

__all__ = ["initialize", "is_initialized", "rank", "size", "global_mesh",
           "available_devices", "world_changed"]

_LOG = logging.getLogger("mxnet_tpu.dist")

_initialized = [False]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Join the multi-host job. Maps reference env vars:
    DMLC_PS_ROOT_URI/PORT -> coordinator, DMLC_NUM_WORKER -> num_processes,
    DMLC_WORKER_ID -> process_id. (reference: launch via tools/launch.py).

    Joining races the coordinator's startup on real pods, so the
    connection is retried with exponential backoff + jitter:
    ``MXNET_DIST_INIT_RETRIES`` attempts (default 3),
    ``MXNET_DIST_INIT_TIMEOUT`` seconds per attempt (default: jax's).
    Exhausting the budget raises an ``MXNetError`` naming the
    coordinator instead of leaking a raw RPC error."""
    if _initialized[0]:
        return
    coordinator_address = coordinator_address or _coord_from_env()
    num_processes = num_processes or get_env("DMLC_NUM_WORKER", None, int)
    process_id = process_id if process_id is not None \
        else get_env("DMLC_WORKER_ID", None, int)
    if coordinator_address is None:
        # single-process: nothing to join
        _initialized[0] = True
        return
    retries = max(1, get_env("MXNET_DIST_INIT_RETRIES", 3, int))
    timeout = get_env("MXNET_DIST_INIT_TIMEOUT", None, float)
    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = timeout
    last_err = None
    for attempt in range(retries):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **kwargs)
            _initialized[0] = True
            return
        except Exception as e:   # jax surfaces RPC failures untyped
            last_err = e
            if attempt + 1 < retries:
                delay = min(30.0, 0.5 * (2 ** attempt)) \
                    * (1.0 + 0.25 * _pyrandom.random())
                _LOG.warning(
                    "dist.initialize attempt %d/%d against %s failed "
                    "(%s: %s); retrying in %.1fs", attempt + 1, retries,
                    coordinator_address, type(e).__name__, e, delay)
                time.sleep(delay)
    raise MXNetError(
        f"could not join the distributed job: coordinator "
        f"{coordinator_address} (process_id={process_id}, "
        f"num_processes={num_processes}) unreachable after {retries} "
        f"attempts; last error: {type(last_err).__name__}: {last_err}. "
        "Check DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT and that the "
        "coordinator process is up; tune MXNET_DIST_INIT_RETRIES/"
        "MXNET_DIST_INIT_TIMEOUT.") from last_err


def _coord_from_env() -> Optional[str]:
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
    if uri:
        return f"{uri}:{port}"
    return None


def is_initialized() -> bool:
    return _initialized[0]


def rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def size() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def global_mesh(axes=None):
    """Mesh over ALL devices across hosts: intra-host axes ride ICI, the
    cross-host axis rides DCN (reference dist kvstore topology)."""
    from .mesh import make_mesh
    axes = axes or {"dp": -1}
    return make_mesh(axes, jax.devices())


def available_devices(backend: Optional[str] = None) -> list:
    """The SURVIVING device world, re-queried from the backend on every
    call — never a list cached at import time. This is what elastic mesh
    re-formation (``mx.elastic``) sizes the new mesh from after a device
    loss: a preempted host's devices must not reappear because an old
    module-level list still names them. Devices the chaos harness marked
    revoked (``testing/faults.py`` ``revoke`` action) are excluded, so
    shrink/grow cycles are testable on the virtual CPU mesh where real
    revocation cannot happen."""
    devs = list(jax.devices(backend)) if backend else list(jax.devices())
    try:
        from ..testing.faults import revoked_device_ids
        revoked = revoked_device_ids()
    except Exception:            # pragma: no cover - defensive
        revoked = ()
    if revoked:
        devs = [d for d in devs if d.id not in revoked]
    return devs


def world_changed(devices) -> bool:
    """Whether the currently-available world differs from ``devices`` —
    a device list (or a ``DeviceMesh``) captured when the current mesh
    was formed. True on loss AND on growth: the elastic supervisor
    probes this to decide when to re-form."""
    if hasattr(devices, "mesh"):          # a parallel.mesh.DeviceMesh
        devices = list(devices.mesh.devices.flat)
    cur = {d.id for d in available_devices()}
    return cur != {d.id for d in devices}
