"""Multi-host distributed runtime.

Reference analog: ps-lite worker/server/scheduler roles launched by
tools/launch.py with DMLC_* env vars (SURVEY §2.3). TPU-native: one SPMD
program per host over a global mesh; `jax.distributed.initialize` replaces
the tracker, and DCN-spanning XLA collectives replace ZMQ push/pull. The
DMLC_* env names are honored so reference launch scripts keep working.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..base import MXNetError, get_env

__all__ = ["initialize", "is_initialized", "rank", "size", "global_mesh"]

_initialized = [False]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Join the multi-host job. Maps reference env vars:
    DMLC_PS_ROOT_URI/PORT -> coordinator, DMLC_NUM_WORKER -> num_processes,
    DMLC_WORKER_ID -> process_id. (reference: launch via tools/launch.py)."""
    if _initialized[0]:
        return
    coordinator_address = coordinator_address or _coord_from_env()
    num_processes = num_processes or get_env("DMLC_NUM_WORKER", None, int)
    process_id = process_id if process_id is not None \
        else get_env("DMLC_WORKER_ID", None, int)
    if coordinator_address is None:
        # single-process: nothing to join
        _initialized[0] = True
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized[0] = True


def _coord_from_env() -> Optional[str]:
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
    if uri:
        return f"{uri}:{port}"
    return None


def is_initialized() -> bool:
    return _initialized[0]


def rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def size() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def global_mesh(axes=None):
    """Mesh over ALL devices across hosts: intra-host axes ride ICI, the
    cross-host axis rides DCN (reference dist kvstore topology)."""
    from .mesh import make_mesh
    axes = axes or {"dp": -1}
    return make_mesh(axes, jax.devices())
