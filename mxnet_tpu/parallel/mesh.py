"""Device-mesh management.

The reference automates only data parallelism (kvstore) and leaves model
parallelism to manual per-layer ctx placement (SURVEY §2.3). Here the mesh is
first-class: axes named 'dp'/'tp'/'pp'/'sp'/'ep' by convention, sharding
attached per-array with NamedSharding, XLA emits collectives over ICI.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["DeviceMesh", "make_mesh", "current_mesh", "data_parallel_mesh",
           "shard_batch", "replicate", "shard_params", "zero_shard_pad",
           "zero_shard_sharding", "place_on_mesh", "P"]

_state = threading.local()


class DeviceMesh:
    """Named-axis device mesh wrapper (thin over jax.sharding.Mesh)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.mesh.shape)

    @property
    def size(self) -> int:
        return int(onp.prod(list(self.mesh.shape.values())))

    @property
    def devices(self) -> List:
        """Flat device list in formation order — what the elastic
        supervisor diffs against ``parallel.dist.available_devices()``
        to detect a changed world."""
        return list(self.mesh.devices.flat)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def axis_size(self, axis: str) -> int:
        if axis not in self.mesh.shape:
            raise MXNetError(f"mesh has no axis {axis!r}; axes: "
                             f"{self.axis_names}")
        return int(self.mesh.shape[axis])

    def __enter__(self):
        stack = getattr(_state, "stack", None)
        if stack is None:
            stack = _state.stack = []
        stack.append(self)
        self.mesh.__enter__()
        return self

    def __exit__(self, *exc):
        self.mesh.__exit__(*exc)
        _state.stack.pop()

    def __repr__(self):
        return f"DeviceMesh({self.shape})"


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) \
        -> DeviceMesh:
    """Build a mesh from axis_name->size. Sizes must multiply to the device
    count; a -1 size is inferred."""
    devices = list(devices) if devices is not None else jax.devices()
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(onp.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(onp.prod(sizes))
    if total != len(devices):
        raise MXNetError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices but "
            f"{len(devices)} available")
    arr = onp.array(devices).reshape(sizes)
    return DeviceMesh(Mesh(arr, tuple(names)))


def data_parallel_mesh(num_devices: Optional[int] = None) -> DeviceMesh:
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return make_mesh({"dp": len(devs)}, devs)


def current_mesh() -> Optional[DeviceMesh]:
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


def shard_batch(data: NDArray, mesh: Optional[DeviceMesh] = None,
                axis: str = "dp") -> NDArray:
    """Shard the batch dimension over a mesh axis — the TPU-native
    split_and_load: ONE logical array, batch-sharded; XLA's psum over the
    axis replaces kvstore reduction."""
    mesh = mesh or current_mesh()
    if mesh is None:
        return data
    spec = [None] * data.ndim
    spec[0] = axis
    sharding = mesh.sharding(*spec)
    return NDArray(jax.device_put(data._data, sharding))


def place_on_mesh(mesh: DeviceMesh, axis: str, d):
    """Lay a raw step input out on the mesh the way the fused train step
    consumes it: batch-shard dim0 over ``axis`` when divisible
    (``shard_batch`` semantics), else replicate; arrays already resident
    on this mesh pass through untouched. Works on jax arrays / numpy /
    python scalars (non-array leaves pass through). This is the sharding
    contract the device prefetcher (gluon/data/prefetcher.py) stages
    batches with so the host→device copy overlaps the previous step."""
    import jax.numpy as jnp
    if not hasattr(d, "shape"):
        return d
    sh = getattr(d, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh == mesh.mesh:
        return d
    d = jnp.asarray(d)
    n = int(mesh.shape[axis])
    if d.ndim >= 1 and d.shape[0] and d.shape[0] % n == 0:
        spec = P(axis, *([None] * (d.ndim - 1)))
        return jax.device_put(d, NamedSharding(mesh.mesh, spec))
    return jax.device_put(d, NamedSharding(mesh.mesh, P()))


def replicate(data: NDArray, mesh: Optional[DeviceMesh] = None) -> NDArray:
    mesh = mesh or current_mesh()
    if mesh is None:
        return data
    return NDArray(jax.device_put(data._data, mesh.sharding()))


def zero_shard_pad(n: int, num_shards: int) -> int:
    """Smallest multiple of ``num_shards`` >= ``n`` — the padded flat length
    a ZeRO-sharded buffer needs so every replica owns an equal 1/N tile
    (arXiv:2004.13336 pads the weight-update buffers the same way)."""
    if num_shards <= 0:
        raise MXNetError(f"num_shards must be positive, got {num_shards}")
    return -(-n // num_shards) * num_shards


def zero_shard_sharding(mesh: DeviceMesh, axis: str = "dp") -> NamedSharding:
    """NamedSharding that partitions a flat (1-D) buffer's leading dim over
    ``axis`` — the layout optimizer state lives in under the ZeRO-1 sharded
    weight update (gluon/fused_step.py)."""
    mesh.axis_size(axis)  # validates the axis exists
    return mesh.sharding(axis)


def shard_params(params, rules: Sequence[Tuple[str, Tuple]],
                 mesh: Optional[DeviceMesh] = None):
    """Attach NamedShardings to Parameters by name-pattern rules.

    rules: list of (substring, partition_spec_tuple); first match wins; no
    match → replicated. e.g. [("dense.weight", ("tp", None))] shards the
    units dim of every dense weight over the 'tp' axis.
    """
    import re
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError("no active mesh; use `with make_mesh(...)`")
    items = params.items() if hasattr(params, "items") else \
        [(p.name, p) for p in params]
    for name, p in items:
        spec = ()
        for pat, s in rules:
            if re.search(pat, name):
                spec = s
                break
        p.set_sharding(mesh.sharding(*spec))
