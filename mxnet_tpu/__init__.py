"""mxnet_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of Apache MXNet's capabilities (NDArray + autograd +
Gluon + KVStore + data pipeline) designed for TPU hardware: XLA compiles and
fuses every op, ``jax.jit`` backs ``hybridize()``, ``jax.sharding`` meshes +
collectives back the KVStore, and Pallas supplies hand-tuned kernels where
XLA's defaults are not enough.

Import convention matches the reference: ``import mxnet_tpu as mx``.
"""
__version__ = "2.0.0a1"

from . import base
from .base import MXNetError
from .context import (Context, cpu, tpu, gpu, cpu_pinned, current_context,
                      num_tpus, num_gpus, device)
from . import engine
from . import ndarray
from . import ndarray as nd
from .ndarray import random
from . import autograd
from . import util
from .util import is_np_array, set_np, reset_np, use_np

# Subsystems are imported as they land in the build plan (SURVEY §7); each
# line below is enabled once the module exists and its tests pass.
_OPTIONAL_MODULES = [
    ("initializer", None), ("init", None), ("optimizer", None),
    ("lr_scheduler", None), ("kvstore", None), ("kvstore", "kv"),
    ("gluon", None),
    ("metric", None), ("profiler", None), ("numpy", "np"),
    ("numpy_extension", "npx"), ("symbol", None), ("symbol", "sym"),
    ("image", None), ("io", None), ("runtime", None), ("parallel", None),
    ("test_utils", None), ("amp", None), ("recordio", None),
    ("operator", None), ("rtc", None), ("contrib", None),
    ("subgraph", None), ("checkpoint", None), ("testing", None),
    ("analysis", None), ("telemetry", None), ("elastic", None),
    ("serving", None), ("tuning", None), ("library", None),
    ("inspector", None), ("visualization", None), ("visualization", "viz"),
    ("name", None), ("attribute", None), ("error", None), ("log", None),
    ("registry", None),
]
import importlib as _importlib

for _mod, _alias in _OPTIONAL_MODULES:
    try:
        _m = _importlib.import_module(f".{_mod}", __name__)
        globals()[_alias or _mod] = _m
    except ImportError:
        pass

try:
    from .kvstore import KVStore  # noqa: F401
except ImportError:
    pass

# MXNET_COMPILE_CACHE=<dir>: persistent XLA compilation cache — restarts
# and repeated bench warmups load executables from disk instead of
# recompiling (runtime.setup_compile_cache logs hits/misses).
try:
    from .runtime import setup_compile_cache as _setup_compile_cache
    _setup_compile_cache()
except Exception:   # the cache is an optimization; never block import
    pass

try:
    from .attribute import AttrScope  # noqa: F401  (reference __init__:72)
except ImportError:
    pass


def tpu_context_available() -> bool:
    return num_tpus() > 0
