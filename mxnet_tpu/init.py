"""``mx.init`` alias namespace (reference exposes initializers both ways)."""
from .initializer import *  # noqa: F401,F403
from .initializer import __all__  # noqa: F401
