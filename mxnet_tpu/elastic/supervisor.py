"""Elastic training supervisor: keep a run alive across device loss.

``ElasticSupervisor`` wraps the ``gluon.TrainLoop`` pipeline in the
recovery state machine a spot/preemptible fleet needs (TensorFlow's
checkpoint/restore discipline, arXiv:1605.08695 §4.3; the MLPerf pod
playbook, arXiv:1909.09756) — composed entirely from existing
machinery: PR 3's layout-free atomic checkpoints (a dp=N save resumes
on a dp=M mesh), PR 5's bounded dispatch window, and the PR 6-8
watchdog/anomaly channel.

State machine (one ``run()`` call)::

        FORM ──────────► TRAIN ──────────► DONE
          ▲      build+    │  step loop,     (final ckpt)
          │      restore   │  probes
          │                ├── preemption notice ──► GRACE SAVE ► exit
          │                ├── world grew ──► planned re-form ─┐
          │                └── device_lost / transient /       │
          │                    stall escalation ──► RECOVER ───┤
          └────────────────────────────────────────────────────┘
               discard in-flight steps after the last retired one,
               bounded retries + exponential backoff, re-form the mesh
               at the surviving world, recompile, restore newest valid
               checkpoint (dp=N→dp=M reshard), continue

Every recovery produces one structured :class:`RecoveryLog` event
``{cause, lost_devices, old_dp, new_dp, restored_step, downtime_s}``
exported through the ``mx_elastic_*`` telemetry series.

The hot loop stays sync-free: per-step losses are held as async
handles and only read after the run leaves the transfer-guard hot
region, so a supervised run passes ``MXNET_TRANSFER_GUARD=raise`` with
zero unblessed syncs (the chaos test pins it).
"""
from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..parallel import dist as _dist
from ..parallel.mesh import make_mesh
from . import detect

__all__ = ["ElasticSupervisor", "ElasticResult", "RecoveryLog",
           "StallEscalation", "recovery_log"]

_LOG = logging.getLogger("mxnet_tpu.elastic")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


class StallEscalation(MXNetError):
    """Marker raised by the supervisor's own step loop when the
    watchdog's ``stall`` anomalies crossed the escalation threshold —
    routed through the same recovery path as a device loss
    (``detect.classify`` maps it to cause ``stall``)."""


# ---------------------------------------------------------------- log
class RecoveryLog:
    """Bounded ring of structured recovery events + their telemetry.

    Each event: ``{cause, lost_devices, old_dp, new_dp, restored_step,
    discarded_steps, downtime_s, step, time_unix}``; recording one
    increments ``mx_elastic_recoveries_total{cause=}``, observes the
    downtime histogram, updates the world-size gauge, and emits one
    ``mx-recovery`` JSON log line.
    """

    def __init__(self, max_events: int = 256):
        # bare on purpose: failure-path leaf: must work when the audit itself is suspect
        self._lock = threading.Lock()  # mx-lint: allow=MXA009
        self._events: "deque[dict]" = deque(maxlen=max_events)
        t = _telemetry()
        reg = t.registry()
        self._c_rec = reg.counter(t.names.ELASTIC_RECOVERIES,
                                  label_key="cause")
        self._h_down = reg.histogram(t.names.ELASTIC_DOWNTIME_SECONDS)
        self._g_world = reg.gauge(t.names.ELASTIC_WORLD_SIZE)

    def record(self, cause: str, lost_devices: List[str], old_dp: int,
               new_dp: int, restored_step: int, downtime_s: float,
               discarded_steps: int = 0, step=None) -> dict:
        evt = {"cause": cause, "lost_devices": list(lost_devices),
               "old_dp": int(old_dp), "new_dp": int(new_dp),
               "restored_step": int(restored_step),
               "discarded_steps": int(discarded_steps),
               "downtime_s": float(downtime_s), "step": step,
               "time_unix": time.time()}
        with self._lock:
            self._events.append(evt)
        self._c_rec.inc(label=cause)
        self._h_down.observe(float(downtime_s))
        self._g_world.set(new_dp)
        _LOG.warning("mx-recovery %s", json.dumps(evt))
        return evt

    def set_world(self, n: int):
        self._g_world.set(int(n))

    def events(self, cause: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if cause is None else [e for e in evs
                                          if e["cause"] == cause]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def table(self) -> str:
        """Human-readable event table (tools/diagnose.py --elastic)."""
        evs = self.events()
        if not evs:
            return "(no recovery events)"
        hdr = (f"{'cause':<12} {'lost':>4} {'dp':>7} {'restored':>8} "
               f"{'discard':>7} {'downtime':>10}")
        rows = [hdr, "-" * len(hdr)]
        for e in evs:
            rows.append(
                f"{e['cause']:<12} {len(e['lost_devices']):>4} "
                f"{e['old_dp']:>3}->{e['new_dp']:<3} "
                f"{e['restored_step']:>8} {e['discarded_steps']:>7} "
                f"{e['downtime_s']*1e3:>8.1f}ms")
        return "\n".join(rows)


_log: Optional[RecoveryLog] = None
# bare on purpose: failure-path leaf: must work when the audit itself is suspect
_log_lock = threading.Lock()  # mx-lint: allow=MXA009


def recovery_log() -> RecoveryLog:
    """The process-global recovery log (what bench legs and diagnose
    read; every supervisor records here unless given its own)."""
    global _log
    if _log is None:
        with _log_lock:
            if _log is None:
                _log = RecoveryLog()
    return _log


# ---------------------------------------------------------------- result
class ElasticResult:
    """What one ``ElasticSupervisor.run`` produced."""

    def __init__(self, losses: dict, events: List[dict], preempted: bool,
                 final_step: int, world_size: int, retries: int):
        self.losses = losses            # batch index -> summed host loss
        self.events = events            # this run's RecoveryLog events
        self.preempted = preempted
        self.final_step = final_step
        self.world_size = world_size
        self.retries = retries

    @property
    def recoveries(self) -> int:
        return len(self.events)

    def __repr__(self):
        return (f"ElasticResult(final_step={self.final_step}, "
                f"world={self.world_size}, recoveries={self.recoveries},"
                f" preempted={self.preempted})")


# ---------------------------------------------------------------- supervisor
class ElasticSupervisor:
    """Keep a training run alive across device loss, preemption, and
    transient step failures.

    ::

        def build():                       # deterministic!
            mx.random.seed(7)
            net = ...; net.initialize()
            trainer = Trainer(net.collect_params(), "adam", {...})
            return net, trainer, gloss.SoftmaxCrossEntropyLoss()

        sup = mx.elastic.ElasticSupervisor(
            build, checkpoint_dir="ckpts/run1",
            mesh_axes={"dp": -1}, checkpoint_every=50)
        result = sup.run(batch_fn, total_steps=10_000)

    ``build()`` constructs a FRESH (net, trainer, loss) triple — it runs
    once per mesh formation, and must be deterministic (seed inside):
    the restored checkpoint overwrites params/optimizer state/RNG, so
    recovery is bit-exact from the restored step at the new layout.
    ``batch_fn(i)`` returns the step-``i`` batch tuple and must be
    replayable by index — after a restore the supervisor re-requests
    batches from the restored step.

    Parameters beyond the obvious: ``mesh_axes`` (e.g. ``{"dp": -1}``,
    sized to the surviving world at each formation; ``None`` = no mesh,
    plain fused mode), ``max_retries``/``backoff_base`` (bounded
    exponential backoff between recovery attempts; one retired step of
    forward progress resets the budget), ``min_devices`` (below it the
    world is unrecoverable), ``max_world`` (cap formation size),
    ``grow``/``probe_every`` (re-form larger when ``parallel.dist
    .world_changed`` sees devices return), ``stall_escalation`` (N
    ``stall`` anomalies since the last recovery escalate into one;
    0 = off), ``recover`` (default ``MXNET_ELASTIC``; False =
    propagate every failure).
    """

    RECOVERABLE = ("device_lost", "transient", "stall")

    def __init__(self, build: Callable, checkpoint_dir: str, *,
                 mesh_axes: Optional[dict] = None, axis: str = "dp",
                 checkpoint_every: Optional[int] = 10, keep_last: int = 3,
                 max_retries: Optional[int] = None,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 min_devices: int = 1, max_world: Optional[int] = None,
                 grow: bool = True, probe_every: int = 1,
                 stall_escalation: int = 0,
                 inflight: Optional[int] = None,
                 record_losses: bool = True,
                 final_checkpoint: bool = True,
                 recover: Optional[bool] = None,
                 log: Optional[RecoveryLog] = None):
        self._build = build
        self._dir = checkpoint_dir
        self._mesh_axes = dict(mesh_axes) if mesh_axes else None
        self._axis = axis
        self._every = checkpoint_every
        self._keep = keep_last
        self._max_retries = detect.max_retries() if max_retries is None \
            else max(0, int(max_retries))
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._min_devices = max(1, int(min_devices))
        self._max_world = max_world
        self._grow = grow
        self._probe_every = max(0, int(probe_every))
        self._stall_escalation = max(0, int(stall_escalation))
        self._inflight = inflight
        self._record_losses = record_losses
        self._final_checkpoint = final_checkpoint
        self._recover = detect.elastic_enabled() if recover is None \
            else bool(recover)
        self._log = log if log is not None else recovery_log()
        # the TRAINING supervisor deliberately polls only the process-
        # global notice: scoped notices (detect.notice("fleet/...")) are
        # per-replica serving machinery and must not pause training
        self._preempt = detect.notice()

        # run state
        self._loop = None
        self._mesh = None
        self._world: List = []
        self._loss_handles: dict = {}
        self._pending: Optional[dict] = None   # recovery in progress
        self._retries = 0
        self._total_retries = 0
        self._recovered_at = 0
        self._stall_count = 0
        self._escalate = False
        self._events_before = 0

    # ---------------- public surface ----------------
    @property
    def world_size(self) -> int:
        """Devices in the currently-formed world (0 before formation)."""
        return len(self._world)

    @property
    def dp_size(self) -> int:
        """Data-parallel width of the current formation (1 = no mesh)."""
        if self._mesh is not None:
            return int(self._mesh.shape.get(self._axis, 1))
        return 1 if self._world else 0

    @property
    def loop(self):
        """The live TrainLoop (rebuilt at every formation; None between
        a failure and the next formation)."""
        return self._loop

    @property
    def recovery_log(self) -> RecoveryLog:
        return self._log

    @property
    def preemption(self) -> detect.PreemptionNotice:
        return self._preempt

    # ---------------- run ----------------
    def run(self, batch_fn: Callable, total_steps: int) -> ElasticResult:
        """Drive the run to ``total_steps`` (or a graceful preemption
        exit), recovering along the way. Returns an
        :class:`ElasticResult`; raises when the failure is fatal, the
        retry budget is exhausted, or recovery is disabled."""
        wd = _telemetry().watchdog()
        if self._stall_escalation:
            wd.subscribe(self._on_anomaly)
        self._preempt.install()
        self._loss_handles = {}
        self._retries = self._total_retries = 0
        self._stall_count = 0
        self._escalate = False
        self._events_before = len(self._log)
        preempted = False
        try:
            while True:
                try:
                    outcome = self._segment(batch_fn, total_steps)
                except BaseException as e:
                    cause = self._recoverable(e)
                    if cause is None:
                        raise
                    self._begin_recovery(cause, e)
                    continue
                if outcome == "reform":
                    continue
                preempted = outcome == "preempted"
                break
        finally:
            self._preempt.uninstall()
            if self._stall_escalation:
                wd.unsubscribe(self._on_anomaly)
        final_step = self._loop.global_step if self._loop is not None \
            else 0
        return ElasticResult(
            losses=self._finalize_losses(), preempted=preempted,
            events=self._log.events()[self._events_before:],
            final_step=final_step, world_size=self.world_size,
            retries=self._total_retries)

    # ---------------- the segment loop ----------------
    def _segment(self, batch_fn, total_steps) -> str:
        with contextlib.ExitStack() as stack:
            self._form(stack)
            loop = self._loop
            start = loop.global_step
            for i in range(start, total_steps):
                if self._preempt.requested():
                    self._graceful_preempt(loop)
                    return "preempted"
                if self._escalate:
                    self._escalate = False
                    raise StallEscalation(
                        f"{self._stall_count} watchdog stall episode(s) "
                        f"since the last recovery (threshold "
                        f"{self._stall_escalation}): treating the world "
                        "as unhealthy")
                if self._grow and self._probe_every and i > start \
                        and (i - start) % self._probe_every == 0 \
                        and self._world_grew():
                    self._planned_reform(loop)
                    return "reform"
                loss = loop.step(*batch_fn(i))
                if self._record_losses:
                    self._loss_handles[i] = loss
                if self._retries and loop.global_step > self._recovered_at:
                    self._retries = 0   # forward progress resets budget
            self._finish(loop)
            return "done"

    def _form(self, stack):
        """FORM: size the world from the surviving devices, build a
        fresh (net, trainer, loss) on it, auto-resume from the newest
        valid checkpoint, and (when a recovery is pending) complete the
        RecoveryLog event with the restored step and downtime."""
        from ..gluon.fused_step import TrainLoop
        devs = self._target_devices()
        if len(devs) < self._min_devices:
            raise MXNetError(
                f"elastic: only {len(devs)} device(s) survive, below "
                f"min_devices={self._min_devices}; cannot re-form")
        self._world = devs
        mesh = None
        if self._mesh_axes is not None and len(devs) >= 2:
            mesh = make_mesh(dict(self._mesh_axes), devs)
            stack.enter_context(mesh)
        self._mesh = mesh
        self._log.set_world(len(devs))
        net, trainer, loss_blk = self._build()
        self._loop = TrainLoop(
            net, trainer, loss_blk, checkpoint_dir=self._dir,
            checkpoint_every=self._every, keep_last=self._keep,
            resume=True, inflight=self._inflight)
        self._recovered_at = self._loop.global_step
        if self._pending is not None:
            p, self._pending = self._pending, None
            restored = self._loop.global_step
            # replayed steps overwrite their loss slots; drop handles
            # of discarded in-flight work explicitly (their buffers may
            # be donated away or poisoned)
            for k in [k for k in self._loss_handles if k >= restored]:
                del self._loss_handles[k]
            self._log.record(
                cause=p["cause"], lost_devices=p["lost"],
                old_dp=p["old_dp"], new_dp=self.dp_size,
                restored_step=restored,
                discarded_steps=p["discarded"],
                downtime_s=time.monotonic() - p["t0"], step=p["step"])
            _LOG.warning(
                "elastic: recovered (%s) at dp=%d, restored step %d",
                p["cause"], self.dp_size, restored)

    def _target_devices(self) -> List:
        devs = _dist.available_devices()
        if self._max_world is not None:
            devs = devs[:self._max_world]
        return devs

    def _world_grew(self) -> bool:
        if not _dist.world_changed(self._world):
            return False
        return len(self._target_devices()) > len(self._world)

    # ---------------- recovery ----------------
    def _recoverable(self, exc) -> Optional[str]:
        """The cause string when recovery should run, else None."""
        if not self._recover:
            return None
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            return None
        cause = detect.classify(exc)
        if cause not in self.RECOVERABLE:
            return None
        return cause

    def _begin_recovery(self, cause: str, exc: BaseException):
        """RECOVER (first half): tear the failed formation down —
        retire what completed, discard in-flight steps after the last
        retired one — check the retry budget, back off, and leave a
        pending event for ``_form`` to complete."""
        t0 = time.monotonic()
        old_dp = self.dp_size
        old_world = list(self._world)
        step = self._loop.global_step if self._loop is not None else None
        # belt-and-braces anomaly: chain-marked, so when the failure
        # already traversed an instrumented seam this is a no-op — a
        # device loss surfacing through an un-instrumented path still
        # gets its exactly-one device_lost event
        if cause == "device_lost":
            detect.maybe_record_device_lost(exc, "elastic supervisor",
                                            step=step)
        discarded = self._teardown(abandon=True)
        alive = {d.id for d in _dist.available_devices()}
        lost = [str(d) for d in old_world if d.id not in alive]
        self._retries += 1
        self._total_retries += 1
        self._stall_count = 0
        self._escalate = False
        if self._retries > self._max_retries:
            raise MXNetError(
                f"elastic: recovery budget exhausted ({self._retries - 1}"
                f" consecutive attempts, MXNET_ELASTIC_MAX_RETRIES="
                f"{self._max_retries}) — last failure: "
                f"{type(exc).__name__}: {exc}") from exc
        delay = min(self._backoff_max,
                    self._backoff_base * (2 ** (self._retries - 1)))
        _LOG.warning(
            "elastic: %s at step %s (%s: %s); recovery attempt %d/%d "
            "in %.1fs", cause, step, type(exc).__name__, exc,
            self._retries, self._max_retries, delay)
        if delay > 0:
            time.sleep(delay)
        self._pending = {"cause": cause, "lost": lost, "old_dp": old_dp,
                         "discarded": discarded, "step": step, "t0": t0}

    def _planned_reform(self, loop):
        """The world GREW back: drain the window, commit a checkpoint at
        the current step, and re-form larger — a zero-discard recovery
        with cause ``grow``."""
        t0 = time.monotonic()
        old_dp = self.dp_size
        step = loop.global_step
        _LOG.warning(
            "elastic: world grew (%d -> %d available); re-forming",
            len(self._world), len(self._target_devices()))
        loop.synchronize()
        loop.save_checkpoint(block=True)
        loop.wait()
        self._teardown(abandon=False)
        self._pending = {"cause": "grow", "lost": [], "old_dp": old_dp,
                         "discarded": 0, "step": step, "t0": t0}

    def _teardown(self, abandon: bool) -> int:
        """Dismantle the current formation; returns the number of
        in-flight steps discarded."""
        loop, self._loop = self._loop, None
        self._mesh = None
        discarded = 0
        if loop is None:
            return 0
        try:
            if abandon:
                _retired, dropped = loop.discard_inflight()
                discarded = len(dropped)
            else:
                loop.synchronize()
        except Exception:        # pragma: no cover - defensive
            _LOG.warning("elastic: window teardown failed", exc_info=True)
        try:
            # an async checkpoint write may be in flight — it is host-
            # side work unaffected by device loss; let it publish so
            # the restore sees the newest state
            loop.wait()
        except Exception as e:
            _LOG.warning("elastic: in-flight checkpoint write failed "
                         "during teardown: %s", e)
        return discarded

    # ---------------- graceful exits ----------------
    def _graceful_preempt(self, loop):
        """GRACE SAVE: the preemption notice arrived — drain the window
        and commit the urgent final checkpoint inside the grace
        window."""
        t0 = time.monotonic()
        grace = detect.preemption_grace_sec()
        try:
            loop.synchronize()
        except Exception:
            _LOG.warning("elastic: drain on preemption failed; "
                         "abandoning in-flight steps", exc_info=True)
            loop.discard_inflight()
        loop.save_checkpoint(block=True)
        loop.wait()
        took = time.monotonic() - t0
        t = _telemetry()
        t.registry().counter(t.names.ELASTIC_PREEMPTIONS).inc()
        if took > grace:
            _LOG.error(
                "elastic: grace-window save took %.1fs, EXCEEDING "
                "MXNET_PREEMPTION_GRACE_SEC=%.1fs — raise the grace "
                "window or lower checkpoint size", took, grace)
        else:
            _LOG.warning(
                "elastic: preemption checkpoint committed at step %d "
                "in %.1fs (%.1fs grace remaining)", loop.global_step,
                took, grace - took)
        self._log.record(
            cause="preemption", lost_devices=[], old_dp=self.dp_size,
            new_dp=self.dp_size, restored_step=loop.global_step,
            downtime_s=took, step=loop.global_step)

    def _finish(self, loop):
        loop.synchronize()
        if loop.checkpoint_manager is not None and self._final_checkpoint:
            loop.save_checkpoint(block=True)
        loop.wait()

    # ---------------- anomaly subscription ----------------
    def _on_anomaly(self, evt: dict):
        """Watchdog-channel callback (telemetry.watchdog().subscribe):
        counts ``stall`` episodes and raises the escalation flag the
        step loop converts into a recovery."""
        if evt.get("kind") != "stall":
            return
        self._stall_count += 1
        if self._stall_count >= self._stall_escalation > 0:
            self._escalate = True

    # ---------------- loss finalize ----------------
    def _finalize_losses(self) -> dict:
        """Read the retained async loss handles — OUTSIDE the hot loop,
        after everything retired, so the supervised run itself stays
        sync-free under MXNET_TRANSFER_GUARD=raise."""
        if not self._record_losses:
            return {}
        losses = {}
        for i, h in sorted(self._loss_handles.items()):
            try:
                d = h._data if isinstance(h, NDArray) else h
                losses[i] = float(onp.asarray(d).sum())
            except Exception:    # a handle poisoned by the failure
                _LOG.debug("loss handle for step %d unreadable", i,
                           exc_info=True)
        return losses
