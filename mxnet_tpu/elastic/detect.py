"""Device-loss detection, failure classification, preemption notices.

The three detection seams the elastic supervisor recovers from
(docs/ROBUSTNESS.md "Elastic training"):

1. **Runtime errors at the dispatch seams.** PjRt surfaces a lost or
   preempted device as an ``XlaRuntimeError`` whose message carries one
   of a small set of patterns ("device lost", "TPU is unhealthy", ...).
   :func:`maybe_record_device_lost` classifies an escaping exception at
   the fused-step call, the dispatch-window retire, and the device_put
   staging carry — the same seams PR 7 instruments for OOM — and emits
   exactly ONE ``device_lost`` anomaly per failure on the watchdog
   channel, however nested the seams (the exception chain is marked,
   the OOM-forensics discipline).
2. **Preemption notices.** Spot/preemptible hosts get a SIGTERM (or a
   maintenance-event signal) with a grace window before the hard kill.
   :class:`PreemptionNotice` converts the signal into a flag the
   supervisor polls each step, so the run drains its window and commits
   an urgent final checkpoint inside ``MXNET_PREEMPTION_GRACE_SEC``.
3. **Stall escalation.** A hung device often produces no error at all —
   just a retire that never completes in time. The watchdog's ``stall``
   anomalies reach the supervisor through the anomaly channel's
   subscription callback (``telemetry.watchdog().subscribe``), and
   repeated episodes escalate into a recovery.

Everything here is import-light (telemetry + faults + jax) so the
engine and fused-step seams can reach it lazily without cycles.
"""
from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
import time
from typing import Optional

__all__ = ["is_device_lost", "classify", "maybe_record_device_lost",
           "device_lost_guard", "PreemptionNotice", "notice",
           "clear_scoped_notices", "elastic_enabled", "armed",
           "max_retries", "preemption_grace_sec"]

_LOG = logging.getLogger("mxnet_tpu.elastic")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# ---------------------------------------------------------------- env gates
def elastic_enabled(default: bool = True) -> bool:
    """``MXNET_ELASTIC``: whether an :class:`~mxnet_tpu.elastic
    .ElasticSupervisor` auto-recovers (default yes once you built one);
    ``0``/``off`` turns the supervisor into a plain runner that
    propagates every failure — the A/B switch for chaos attribution."""
    v = os.environ.get("MXNET_ELASTIC")
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "off", "false", "no")


def armed() -> bool:
    """Whether ``MXNET_ELASTIC`` is EXPLICITLY set truthy — the gate for
    ambient integrations (bench legs attaching recovery stats) that
    should stay silent unless the operator opted in."""
    v = os.environ.get("MXNET_ELASTIC")
    return v is not None and v.strip().lower() not in (
        "", "0", "off", "false", "no")


def max_retries(default: int = 3) -> int:
    """``MXNET_ELASTIC_MAX_RETRIES``: consecutive recovery attempts
    without forward progress before the supervisor gives up and
    re-raises (progress — one retired step past the restored point —
    resets the budget)."""
    try:
        v = int(os.environ.get("MXNET_ELASTIC_MAX_RETRIES", default))
    except (TypeError, ValueError):
        return default
    return max(0, v)


def preemption_grace_sec(default: float = 30.0) -> float:
    """``MXNET_PREEMPTION_GRACE_SEC``: the budget between the preemption
    notice and the hard kill — the urgent final checkpoint must commit
    inside it (exceeding it is logged; the checkpoint is attempted
    regardless)."""
    try:
        v = float(os.environ.get("MXNET_PREEMPTION_GRACE_SEC", default))
    except (TypeError, ValueError):
        return default
    return v if v > 0 else default


# ---------------------------------------------------------------- classify
#: lowercase substrings of PjRt/XlaRuntimeError messages that mean the
#: DEVICE (not the program) failed — curated from TPU/GPU runtime error
#: strings; the chaos harness's DeviceRevokedError mimics the first
_DEVICE_LOST_MARKERS = (
    "device lost",
    "device_lost",
    "device is lost",
    "tpu is unhealthy",
    "chip has been removed",
    "device has been removed",
    "removed from the system",
    "hardware failure",
    "worker has been preempted",
    "slice health check failed",
    "failed to enumerate devices",
    "device failed",
    "halt requested",
    "heartbeat timeout",
)


def _chain(exc):
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        yield exc
        exc = exc.__cause__ or exc.__context__


def is_device_lost(exc: BaseException) -> bool:
    """Whether ``exc`` (or anything in its cause chain) is a device
    loss/revocation — a failure of the HARDWARE world, recoverable by
    re-forming the mesh at the surviving device count, as opposed to a
    failure of the program (which would just fail again)."""
    for e in _chain(exc):
        if type(e).__name__ == "DeviceRevokedError":
            return True
        msg = str(e).lower()
        if any(m in msg for m in _DEVICE_LOST_MARKERS):
            return True
    return False


def classify(exc: BaseException) -> str:
    """Failure taxonomy for the recovery decision:

    - ``device_lost`` — the world shrank; re-form the mesh and restore;
    - ``stall`` — escalated watchdog stall episodes (supervisor.py's
      :class:`StallEscalation` marker);
    - ``oom`` — allocation failure; NOT recovered by default (a smaller
      world only raises per-device load — fix the budget instead);
    - ``transient`` — an ``OSError``-family blip (IO hiccup, injected
      fault) worth a bounded retry from the last checkpoint;
    - ``fatal`` — everything else (a shape error re-fails forever).
    """
    if is_device_lost(exc):
        return "device_lost"
    for e in _chain(exc):
        if type(e).__name__ == "StallEscalation":
            return "stall"
    t = _telemetry()
    if t.memory.is_resource_exhausted(exc):
        return "oom"
    for e in _chain(exc):
        if isinstance(e, OSError):
            return "transient"
    return "fatal"


def maybe_record_device_lost(exc: BaseException, seam: str,
                             step=None) -> bool:
    """If ``exc`` is a device loss not already handled at an inner seam,
    emit exactly one ``device_lost`` anomaly on the watchdog channel
    (ring + ``mx_anomalies_total{kind=device_lost}`` + one JSON log
    line + subscription callbacks). Returns True when the event fired.
    Never raises — detection must not mask the original error."""
    try:
        if not is_device_lost(exc):
            return False
        for e in _chain(exc):
            if getattr(e, "_mx_device_lost_handled", False):
                return False
        try:
            exc._mx_device_lost_handled = True
        except Exception:        # pragma: no cover - frozen exc types
            pass
        lost = _lost_device_count()
        _telemetry().watchdog().report(
            "device_lost", step, value=lost or None,
            message=f"device loss at {seam}"
                    + (f" (step {step})" if step is not None else "")
                    + (f"; {lost} device(s) missing from the world"
                       if lost else "")
                    + f": {type(exc).__name__}: {exc}")
        return True
    except Exception:            # pragma: no cover - defensive
        _LOG.warning("device-lost detection failed", exc_info=True)
        return False


def _lost_device_count() -> int:
    try:
        import jax
        from ..parallel.dist import available_devices
        return max(0, len(jax.devices()) - len(available_devices()))
    except Exception:            # pragma: no cover - defensive
        return 0


@contextlib.contextmanager
def device_lost_guard(seam: str, step=None):
    """Wrap a dispatch seam: an escaping device loss gets its anomaly
    recorded (once, however nested the seams) and propagates
    unchanged — the companion of ``telemetry.memory.oom_guard``."""
    try:
        yield
    except BaseException as e:
        maybe_record_device_lost(e, seam, step=step)
        raise


# ---------------------------------------------------------------- preemption
class PreemptionNotice:
    """Signal-to-flag bridge for the preemption grace window.

    ``install()`` (main thread) replaces the handlers of the given
    signals with one that records the notice time and sets a flag — it
    deliberately does NOT raise into the training loop: the supervisor
    polls :meth:`requested` at its step boundary, where the dispatch
    window can be drained and the final checkpoint committed cleanly.
    ``trigger()`` raises the flag programmatically (tests, cloud
    maintenance-event watchers that poll a metadata endpoint).

    ``scope`` (default None = the process-global notice) names the
    subset of the process this notice concerns — e.g. one serving
    replica in a :class:`~mxnet_tpu.serving.FleetController`, so a
    single host's preemption drains exactly that replica while the
    rest keep serving. Scoped notices live in a registry keyed by the
    scope string (:func:`notice`); consumers that poll a scope must
    ALSO poll the global notice (a process-wide SIGTERM still drains
    everyone) — :meth:`requested` on a scoped notice does exactly
    that.
    """

    def __init__(self, scope: Optional[str] = None):
        self.scope = scope
        self._event = threading.Event()
        self._time: Optional[float] = None
        self._prev: dict = {}
        # bare on purpose: failure-path leaf: must work when the audit itself is suspect
        self._lock = threading.Lock()  # mx-lint: allow=MXA009

    def install(self, signals=(signal.SIGTERM,)):
        """Arm the handlers; safe to call repeatedly. Off the main
        thread (where signal.signal raises) installation is skipped
        with a warning — :meth:`trigger` still works."""
        for sig in signals:
            with self._lock:
                if sig in self._prev:
                    continue
            try:
                prev = signal.signal(sig, self._handler)
            except ValueError:   # not the main thread
                _LOG.warning(
                    "cannot install preemption handler for signal %s "
                    "off the main thread; rely on trigger()", sig)
                continue
            with self._lock:
                self._prev[sig] = prev

    def uninstall(self):
        """Restore the previous handlers and clear the flag."""
        with self._lock:
            prev, self._prev = dict(self._prev), {}
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self.clear()

    def _handler(self, signum, frame):      # pragma: no cover - signal
        self.trigger(signum)

    def trigger(self, signum=None):
        """Raise the preemption flag (what the signal handler does)."""
        with self._lock:
            if self._time is None:
                self._time = time.time()
        self._event.set()
        _LOG.warning(
            "preemption notice received (%s%s): requesting grace-window "
            "final checkpoint (MXNET_PREEMPTION_GRACE_SEC=%.0fs)",
            f"signal {signum}" if signum is not None else "programmatic",
            f", scope {self.scope!r}" if self.scope else "",
            preemption_grace_sec())

    def requested(self) -> bool:
        """Whether this notice (or, for a SCOPED notice, the process-
        global one too — a process-wide SIGTERM concerns every scope)
        has fired."""
        if self._event.is_set():
            return True
        return self.scope is not None and _notice._event.is_set()

    @property
    def notice_time(self) -> Optional[float]:
        return self._time

    def remaining_grace(self) -> float:
        """Seconds left in the grace window (full budget before any
        notice)."""
        grace = preemption_grace_sec()
        if self._time is None:
            return grace
        return grace - (time.time() - self._time)

    def clear(self):
        self._event.clear()
        with self._lock:
            self._time = None


_notice = PreemptionNotice()
# bare on purpose: failure-path leaf: must work when the audit itself is suspect
_scoped_lock = threading.Lock()  # mx-lint: allow=MXA009
_scoped: dict = {}


def notice(scope: Optional[str] = None) -> PreemptionNotice:
    """With no ``scope``: the process-global preemption notice (one
    SIGTERM concerns every supervisor in the process). With a scope
    string: the per-scope notice from the registry (created on first
    use) — triggering it drains exactly the consumers polling that
    scope (e.g. one fleet replica), while everyone else keeps running;
    a scoped notice's :meth:`~PreemptionNotice.requested` also honours
    the process-global flag, so a real SIGTERM still drains all."""
    if scope is None:
        return _notice
    with _scoped_lock:
        n = _scoped.get(scope)
        if n is None:
            n = _scoped[scope] = PreemptionNotice(scope=scope)
        return n


def clear_scoped_notices():
    """Drop every scoped notice (test teardown / fleet shutdown)."""
    with _scoped_lock:
        _scoped.clear()
