"""mx.elastic — elastic, preemption-driven training supervision.

Composes the checkpoint, dispatch-window, and telemetry subsystems into
automatic recovery (docs/ROBUSTNESS.md "Elastic training"):

- :mod:`.detect` — device-loss classification at the dispatch seams, the
  ``device_lost`` anomaly kind on the watchdog channel, preemption
  (SIGTERM) notices with a grace window, and the ``MXNET_ELASTIC*`` env
  gates;
- :mod:`.supervisor` — :class:`ElasticSupervisor`, which keeps a
  ``gluon.TrainLoop`` run alive across device loss/preemption/transient
  failures: drain+discard the window, re-form the mesh at the surviving
  world, recompile, restore the newest valid atomic checkpoint
  (dp=N→dp=M reshard), continue — with bounded retries and a structured
  :class:`RecoveryLog` exported as ``mx_elastic_*`` telemetry.

The chaos harness lives in ``mxnet_tpu/testing/faults.py`` (``revoke``/
``restore`` actions + the ``step.dispatch``/``window.retire``/
``prefetch.stage`` fault points).
"""
from . import detect                                    # noqa: F401
from .detect import (is_device_lost, classify,          # noqa: F401
                     maybe_record_device_lost, device_lost_guard,
                     PreemptionNotice, notice, clear_scoped_notices,
                     elastic_enabled, armed,
                     max_retries, preemption_grace_sec)

__all__ = ["detect", "is_device_lost", "classify",
           "maybe_record_device_lost", "device_lost_guard",
           "PreemptionNotice", "notice", "clear_scoped_notices",
           "elastic_enabled", "armed",
           "max_retries", "preemption_grace_sec",
           # lazily resolved from .supervisor (needs gluon loaded):
           "supervisor", "ElasticSupervisor", "ElasticResult",
           "RecoveryLog", "StallEscalation", "recovery_log"]

_LAZY = ("ElasticSupervisor", "ElasticResult", "RecoveryLog",
         "StallEscalation", "recovery_log")


def __getattr__(name):
    # the supervisor half pulls in gluon; load it on first use so the
    # lightweight detection half stays importable from the engine seams
    # (import_module, not `from . import`, which would re-enter this
    # __getattr__ through _handle_fromlist)
    if name == "supervisor" or name in _LAZY:
        import importlib
        mod = importlib.import_module(".supervisor", __name__)
        globals()["supervisor"] = mod
        return mod if name == "supervisor" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
