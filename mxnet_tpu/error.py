"""Typed framework errors.

Reference analog: python/mxnet/error.py — a registry mapping error-type
names to Python exception classes (there used to decode C++ FFI error
headers like ``ValueError: ...``; here used by native-boundary code and
kept for API parity) plus ``InternalError``.
"""
from .base import MXNetError

__all__ = ["MXNetError", "InternalError", "register"]

_ERROR_REGISTRY = {}


def register(cls_or_name, cls=None):
    """Register an error class under a name. Usable as a decorator
    (``@register`` on an MXNetError subclass) or as
    ``register("ValueError", ValueError)``."""
    if cls is not None:
        _ERROR_REGISTRY[cls_or_name] = cls
        return cls
    _ERROR_REGISTRY[cls_or_name.__name__] = cls_or_name
    return cls_or_name


def get_error_class(name):
    """Look up a registered error class; MXNetError when unknown."""
    return _ERROR_REGISTRY.get(name, MXNetError)


@register
class InternalError(MXNetError):
    """Internal error in the framework (reference error.py:31)."""

    def __init__(self, msg):
        if "hint:" not in msg:
            msg += ("\nhint: you hit an internal error; please report it "
                    "with the full traceback.")
        super().__init__(msg)


register("ValueError", ValueError)
register("TypeError", TypeError)
register("AttributeError", AttributeError)
register("IndexError", IndexError)
register("NotImplementedError", NotImplementedError)
