"""Custom operators in Python (reference: python/mxnet/operator.py +
src/operator/custom/custom-inl.h).

Reference mechanics: ``CustomOp`` (forward/backward mutating out buffers via
``assign``), ``CustomOpProp`` (shape/type inference + operator factory),
``mx.operator.register``; the C++ side runs Python callbacks on a dedicated
worker pool so they never block engine threads (custom-inl.h:52,103).

TPU-native redesign: the host escape is ``jax.pure_callback`` — the same op
works eagerly AND inside a jit/hybridized trace (XLA calls back to host),
which is the role the reference's callback thread pool played. Autograd
rides the tape with a custom vjp that invokes ``backward`` through the same
escape. The fwd/bwd contract is stateless: ``backward`` receives in_data and
out_data again rather than instance state (instances are not shared between
traced executions).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Sequence

import numpy as onp

import jax
import jax.numpy as jnp

from .base import MXNetError, jx_dtype
from .ndarray.ndarray import NDArray
from .ops.registry import invoke_raw

__all__ = ["CustomOp", "CustomOpProp", "register", "Custom", "get_all_registered"]

_CUSTOM_OPS: Dict[str, type] = {}


class CustomOp:
    """Base class for custom operator implementations (reference
    operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise MXNetError(
            f"{type(self).__name__} does not implement backward")

    def assign(self, dst: NDArray, req: str, src):
        """Write ``src`` into ``dst`` honoring grad_req semantics."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Describes a custom op: arity, shapes, types, and the operator
    factory (reference operator.py CustomOpProp)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad
        self.kwargs: Dict[str, str] = {}

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Class decorator registering a CustomOpProp under ``reg_name``
    (reference mx.operator.register). The op is then invocable as
    ``mx.nd.Custom(*inputs, op_type=reg_name, **kwargs)``."""
    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_OPS[reg_name] = prop_cls
        return prop_cls
    return deco


def get_all_registered() -> List[str]:
    return sorted(_CUSTOM_OPS)


def _run_forward(prop, op, out_shapes, out_dtypes, is_train, *np_inputs):
    ins = [NDArray(jnp.asarray(a)) for a in np_inputs]
    outs = [NDArray(jnp.zeros(s, d)) for s, d in zip(out_shapes, out_dtypes)]
    op.forward(is_train=is_train, req=["write"] * len(outs),
               in_data=ins, out_data=outs, aux=[])
    return tuple(onp.asarray(o._data, dtype=d)
                 for o, d in zip(outs, out_dtypes))


def _run_backward(prop, op, in_shapes, in_dtypes, n_in, n_out, *np_args):
    np_grads = np_args[:n_out]
    np_ins = np_args[n_out:n_out + n_in]
    np_outs = np_args[n_out + n_in:]
    ograds = [NDArray(jnp.asarray(a)) for a in np_grads]
    ins = [NDArray(jnp.asarray(a)) for a in np_ins]
    outs = [NDArray(jnp.asarray(a)) for a in np_outs]
    igrads = [NDArray(jnp.zeros(s, d)) for s, d in zip(in_shapes, in_dtypes)]
    op.backward(req=["write"] * n_in, out_grad=ograds, in_data=ins,
                out_data=outs, in_grad=igrads, aux=[])
    return tuple(onp.asarray(g._data, dtype=d)
                 for g, d in zip(igrads, in_dtypes))


def _make_custom_fn(prop, op, in_shapes, in_dtypes, out_shapes, out_dtypes,
                    is_train):
    """Pure jax function (pure_callback escape) with custom vjp."""
    out_struct = tuple(jax.ShapeDtypeStruct(s, d)
                       for s, d in zip(out_shapes, out_dtypes))
    in_struct = tuple(jax.ShapeDtypeStruct(s, d)
                      for s, d in zip(in_shapes, in_dtypes))

    @jax.custom_vjp
    def custom_fn(*xs):
        return jax.pure_callback(
            functools.partial(_run_forward, prop, op, out_shapes, out_dtypes,
                              is_train), out_struct, *xs)

    def fwd(*xs):
        ys = custom_fn(*xs)
        return ys, (xs, ys)

    def bwd(res, gs):
        xs, ys = res
        gs = gs if isinstance(gs, tuple) else (gs,)
        return jax.pure_callback(
            functools.partial(_run_backward, prop, op, in_shapes, in_dtypes,
                              len(xs), len(gs)), in_struct, *gs, *xs, *ys)

    custom_fn.defvjp(fwd, bwd)
    return custom_fn


def Custom(*data, op_type: str, **kwargs):
    """Invoke a registered custom op on NDArrays (reference mx.nd.Custom)."""
    if op_type not in _CUSTOM_OPS:
        raise MXNetError(f"custom op {op_type!r} is not registered")
    from . import _tape
    cls = _CUSTOM_OPS[op_type]
    str_kwargs = {k: str(v) for k, v in kwargs.items()}
    try:
        prop = cls(**str_kwargs)  # reference passes attrs as strings
    except TypeError:
        prop = cls()
    prop.kwargs = str_kwargs

    in_shapes = [d.shape for d in data]
    in_dtypes = [onp.dtype(d.dtype) for d in data]
    _, out_shapes, _ = prop.infer_shape(list(in_shapes))
    it, ot, _ = prop.infer_type(list(in_dtypes))
    out_dtypes = [onp.dtype(t) for t in ot]
    op = prop.create_operator(None, in_shapes, in_dtypes)
    is_train = _tape.is_recording()

    fn = _make_custom_fn(prop, op, in_shapes, in_dtypes, out_shapes,
                         out_dtypes, is_train)
    n_out = len(out_shapes)
    if n_out == 1:
        return invoke_raw(f"Custom[{op_type}]",
                          lambda *xs: fn(*xs)[0], list(data))
    return invoke_raw(f"Custom[{op_type}]", fn, list(data), n_outputs=n_out)


# expose mx.nd.Custom like the reference's generated wrapper
from . import ndarray as _nd_mod  # noqa: E402
_nd_mod.Custom = Custom
