"""Optimizers.

Reference analog: python/mxnet/optimizer/*.py (19 classes) backed by fused
C++/CUDA update kernels (src/operator/optimizer_op.cc, multi-tensor
multi_sgd_*). TPU-native design: every optimizer's update rule is ONE pure
function (w, g, *states) -> (w', *states') compiled with jax.jit and shared
across all parameters of the same shape — XLA fuses the whole rule into a
single kernel, and buffer donation makes updates in-place in HBM, matching
the reference's fused+inplace update kernels.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "Signum", "SGLD", "DCASGD", "Adam",
           "AdamW", "AdaBelief", "Adamax", "Nadam", "AdaGrad", "GroupAdaGrad",
           "AdaDelta",
           "RMSProp", "Ftrl", "FTML", "LARS", "LAMB", "LANS", "Updater",
           "get_updater", "create", "register"]

_registry: Dict[str, type] = {}


def register(cls):
    """Register an optimizer under its lowercase class name
    (reference Optimizer.register)."""
    _registry[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    try:
        return _registry[name.lower()](**kwargs)
    except KeyError as e:
        raise MXNetError(f"unknown optimizer {name!r}") from e


class Optimizer:
    """Base optimizer (reference optimizer/optimizer.py).

    Subclasses define ``create_state(index, weight)`` and a pure
    ``_update_rule(w, g, lr, wd, t, *states)`` returning (w', states').
    The rule is jitted once with donated buffers.
    """

    # True when the update rule is purely elementwise over the weight (no
    # cross-element reductions like LARS/LAMB trust ratios or GroupAdaGrad
    # row means) AND tolerates vector-valued lr/wd/t. Elementwise rules can
    # run on arbitrary flat 1/N shards of the weight — the property the
    # ZeRO-1 sharded update (gluon/fused_step.py) keys on.
    elementwise_update = True

    def __init__(self, rescale_grad: float = 1.0, param_idx2name=None,
                 wd: float = 0.0, clip_gradient: Optional[float] = None,
                 learning_rate: Optional[float] = None, lr_scheduler=None,
                 multi_precision: bool = False, param_dict=None,
                 begin_num_update: int = 0, use_fused_step: bool = True,
                 **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self._jit_update = None
        self._lr_mult: Dict[Any, float] = {}
        self._wd_mult: Dict[Any, float] = {}

    # ---------------- lr/wd handling ----------------
    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return float(self.lr_scheduler(self.num_update))
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.lr = lr

    def set_learning_rate(self, lr):
        self.lr = lr

    def set_lr_mult(self, args_lr_mult: Dict[Any, float]):
        self._lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[Any, float]):
        self._wd_mult = dict(args_wd_mult)

    def _get_lr(self, index) -> float:
        lr = self.learning_rate
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        # reference optimizer.py precedence: an index-keyed mult wins over
        # a name-keyed one for the same parameter
        if index in self._lr_mult:
            lr *= self._lr_mult[index]
        else:
            lr *= self._lr_mult.get(self.idx2name.get(index, index), 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        if index in self._wd_mult:
            wd *= self._wd_mult[index]
        else:
            wd *= self._wd_mult.get(self.idx2name.get(index, index), 1.0)
        return wd

    def _update_count(self, index):
        cnt = self._index_update_count.get(index, self.begin_num_update) + 1
        self._index_update_count[index] = cnt
        self.num_update = max(cnt, self.num_update)
        return cnt

    # ---------------- state ----------------
    # States are tuples of NDArray handles: mutable like the reference's
    # state NDArrays, while the update math itself is functional + jitted.
    def create_state(self, index, weight: NDArray):
        return ()

    def _zeros_state(self, weight, n: int):
        return tuple(NDArray(jnp.zeros_like(weight._data)) for _ in range(n))

    def create_state_multi_precision(self, index, weight: NDArray):
        if self.multi_precision and weight._data.dtype in (jnp.float16,
                                                           jnp.bfloat16):
            master = NDArray(jnp.asarray(weight._data, jnp.float32))
            return (self.create_state(index, weight), master)
        return self.create_state(index, weight)

    # ---------------- update ----------------
    def _rule(self):
        """Pure update rule; jitted lazily with donated args so XLA updates
        weights in place (the reference's in-place fused kernels)."""
        raise NotImplementedError

    def _jitted(self):
        if self._jit_update is None:
            rule = self._rule()
            has_clip = self.clip_gradient is not None

            # rescale/clip are traced args (NOT closure constants): Trainer
            # changes rescale_grad every step(batch_size) call.
            def stepfn(w, g, lr, wd, t, rescale, clip, states):
                g = g * rescale
                if has_clip:
                    g = jnp.clip(g, -clip, clip)
                return rule(w, g, lr, wd, t, states)

            # donate only optimizer-private state buffers; the weight buffer
            # may be aliased by kvstore entries / user-held NDArrays
            self._jit_update = jax.jit(stepfn, donate_argnums=(7,))
        return self._jit_update

    def _jitted_sparse(self):
        """Lazy row_sparse update (reference optimizer_op.cc sparse
        sgd/adam kernels + optimizer.py lazy_update): the rule runs only on
        the rows named by the gradient's indices — gather rows of weight and
        state, apply the elementwise rule, scatter back. FLOPs and state
        traffic are O(rows touched), not O(vocab).

        MXNET_SPARSE_DONATE=1 additionally donates the weight buffer so the
        scatter is in-place in HBM (off by default: the weight buffer may be
        aliased by other live NDArray handles)."""
        if getattr(self, "_jit_sparse", None) is None:
            import os
            rule = self._rule()
            has_clip = self.clip_gradient is not None
            # distinct compiled signatures (row buckets), recorded at
            # trace time into a SET — stable under jit-cache eviction
            # retraces, unlike jit's internal cache size
            self._sparse_trace_buckets = set()

            def stepfn(w, ids, vals, lr, wd, t, rescale, clip, states):
                self._sparse_trace_buckets.add(int(ids.shape[0]))
                g = vals * rescale
                if has_clip:
                    g = jnp.clip(g, -clip, clip)
                w_rows = jnp.take(w, ids, axis=0)
                s_rows = tuple(jnp.take(s, ids, axis=0) for s in states)
                new_rows, new_s_rows = rule(w_rows, g, lr, wd, t, s_rows)
                new_w = w.at[ids].set(new_rows)
                new_states = tuple(s.at[ids].set(ns)
                                   for s, ns in zip(states, new_s_rows))
                return new_w, new_states

            donate = (0, 8) if os.environ.get(
                "MXNET_SPARSE_DONATE", "0") == "1" else (8,)
            self._jit_sparse = jax.jit(stepfn, donate_argnums=donate)
        return self._jit_sparse

    def _update_one_sparse(self, index, weight, grad, state, t, lr, wd):
        ids = grad._aux["indices"]._data.astype(jnp.int32)
        vals = grad._aux["values"]._data
        # pad the row count to the next power of two so variable
        # unique-token counts share compiled programs instead of retracing
        # per distinct count. Pad ids with vocab (out of bounds): XLA drops
        # OOB scatter rows and clips OOB gather rows, so padding rows are
        # read-and-discarded no-ops with zero-valued gradients.
        n = int(ids.shape[0])
        vocab = int(weight._data.shape[0])
        bucket = 1
        while bucket < n:
            bucket <<= 1
        if bucket > n:
            ids = jnp.pad(ids, (0, bucket - n), constant_values=vocab)
            vals = jnp.pad(vals, ((0, bucket - n),) + ((0, 0),) *
                           (vals.ndim - 1))
        clip = self.clip_gradient if self.clip_gradient is not None else 0.0
        raw_state = tuple(s._data for s in state)
        new_w, new_state = self._jitted_sparse()(
            weight._data, ids, vals, lr, wd, t, self.rescale_grad, clip,
            raw_state)
        weight._data = new_w
        for s, ns in zip(state, new_state):
            s._data = ns

    def fused_step_fn(self):
        """Pure TRACEABLE multi-tensor update: the whole-step fusion
        surface ``Trainer.compile_step`` folds into its one program, and
        the body ``_jitted_multi`` compiles standalone for the eager path.

        Signature: ``(ws, gs, lrs, wds, ts, rescale, clip, states) ->
        (new_ws, new_states)`` where ws/gs/states are tuples over params
        and lrs/wds/ts index per-param hyperparameters (list of scalars
        OR traced 1-d arrays — both support ``[i]``). rescale/clip are
        traced scalars so ``trainer.learning_rate = x`` / per-step batch
        size never force a retrace.

        Under the ZeRO-1 sharded update (gluon/fused_step.py) each ``ws``
        entry is a flat padded 1/N *shard* of one parameter — or of a
        whole bucket of small parameters — and the matching lrs/wds/ts
        entry may be a per-ELEMENT vector built by
        ``pack_shard_hparams``; elementwise rules
        (``elementwise_update``) apply unchanged either way."""
        rule = self._rule()
        has_clip = self.clip_gradient is not None

        def stepfn(ws, gs, lrs, wds, ts, rescale, clip, states):
            new_ws, new_ss = [], []
            for i, (w, g, st) in enumerate(zip(ws, gs, states)):
                g = g * rescale
                if has_clip:
                    g = jnp.clip(g, -clip, clip)
                nw, ns = rule(w, g, lrs[i], wds[i], ts[i], st)
                new_ws.append(nw)
                new_ss.append(ns)
            return tuple(new_ws), tuple(new_ss)

        return stepfn

    def kernel_step_fn(self):
        """The Pallas fused multi-tensor update over flat 1-d shards
        (ops/kernels/opt_update.py), signature-compatible with
        :meth:`fused_step_fn` — or ``None`` when the ``MXNET_PALLAS``
        gate selects the XLA path or this rule is not kernelized
        (exact SGD/Adam only; subclasses may override ``_rule`` so
        they keep the reference path)."""
        from ..ops.kernels.opt_update import kernel_step_fn as _kfn
        return _kfn(self)

    @staticmethod
    def pack_shard_hparams(lrs, wds, ts, member_idx, sizes, padded):
        """Per-shard lr/wd packing for a ZeRO bucket: several small
        parameters concatenated into ONE flat sharded buffer need
        per-ELEMENT hyperparameters. Repeats each member's scalar over its
        flat segment; the pad tail gets lr=wd=0 and t=1 so bias-corrected
        rules (Adam's ``1/(1-beta**t)``) stay finite on the padding.
        Returns (lr_vec f32[padded], wd_vec f32[padded], t_vec i32[padded])
        as plain host arrays — traced jit arguments, never retrace keys."""
        lr_vec = onp.zeros(padded, onp.float32)
        wd_vec = onp.zeros(padded, onp.float32)
        t_vec = onp.ones(padded, onp.int32)
        total = int(onp.sum(sizes))
        lr_vec[:total] = onp.repeat(
            onp.asarray(lrs, onp.float32)[member_idx], sizes)
        wd_vec[:total] = onp.repeat(
            onp.asarray(wds, onp.float32)[member_idx], sizes)
        t_vec[:total] = onp.repeat(
            onp.asarray(ts, onp.int32)[member_idx], sizes)
        return lr_vec, wd_vec, t_vec

    def begin_fused_step(self, indices):
        """Host-side half of a fused whole-train-step: advance the
        per-index update counts (same bookkeeping the eager
        ``_update_multi`` does) and return ``(lrs, wds, ts)`` as small
        host arrays to be passed as TRACED arguments — changing the
        learning rate, a scheduler tick, or weight decay never
        recompiles the step program."""
        ts = [self._update_count(i) for i in indices]
        lrs = [self._get_lr(i) for i in indices]
        wds = [self._get_wd(i) for i in indices]
        return (onp.asarray(lrs, onp.float32), onp.asarray(wds, onp.float32),
                onp.asarray(ts, onp.int32))

    def hparam_snapshot(self) -> dict:
        """Small host-side view of the hyperparameter state driving the
        current step — the lr/clip/update-count context the numerics
        forensics dump records next to the per-layer norm table
        (telemetry/numerics.py; docs/OBSERVABILITY.md "numerics")."""
        try:
            lr = float(self.learning_rate)
        except Exception:        # pragma: no cover - exotic schedulers
            lr = None
        return {
            "optimizer": type(self).__name__,
            "learning_rate": lr,
            "wd": float(getattr(self, "wd", 0.0) or 0.0),
            "rescale_grad": float(self.rescale_grad),
            "clip_gradient": None if self.clip_gradient is None
            else float(self.clip_gradient),
            "num_update": int(self.num_update),
            "multi_precision": bool(getattr(self, "multi_precision",
                                            False)),
        }

    def _jitted_multi(self):
        """Multi-tensor fused step (reference multi_sgd_mom_update,
        src/operator/optimizer_op.cc): ALL parameter updates compile into
        ONE XLA program — one dispatch per optimizer step instead of one
        per parameter."""
        if getattr(self, "_jit_multi", None) is None:
            self._jit_multi = jax.jit(self.fused_step_fn(),
                                      donate_argnums=(7,))
        return self._jit_multi

    def _update_multi(self, indices, weights, grads, states):
        """Fused path for plain (non-multi-precision) states."""
        ts = [self._update_count(i) for i in indices]
        lrs = [self._get_lr(i) for i in indices]
        wds = [self._get_wd(i) for i in indices]
        clip = self.clip_gradient if self.clip_gradient is not None else 0.0
        raw_states = tuple(tuple(s._data for s in st) for st in states)
        new_ws, new_ss = self._jitted_multi()(
            tuple(w._data for w in weights),
            tuple(g._data for g in grads),
            lrs, wds, ts, self.rescale_grad, clip, raw_states)
        for w, nw in zip(weights, new_ws):
            w._data = nw
        for st, ns in zip(states, new_ss):
            for s, n in zip(st, ns):
                s._data = n

    def update(self, index, weight, grad, state):
        """Single-param update (reference Optimizer.update). Lists are the
        reference's multi-tensor form, fused into one XLA program."""
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(index, (list, tuple)):
            plain = all(
                not (isinstance(s, tuple) and len(s) == 2 and
                     isinstance(s[0], tuple) and isinstance(s[1], NDArray) and
                     w._data.dtype in (jnp.float16, jnp.bfloat16))
                for s, w in zip(state, weight)) and not any(
                isinstance(g, RowSparseNDArray) for g in grad)
            if plain and len(index) > 1:
                self._update_multi(list(index), list(weight), list(grad),
                                   list(state))
                return
            for i, w, g, s in zip(index, weight, grad, state):
                self._update_one(i, w, g, s)
        else:
            self._update_one(index, weight, grad, state)

    update_multi_precision = update

    def _update_one(self, index, weight: NDArray, grad: NDArray, state):
        t = self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        master = None
        if isinstance(state, tuple) and len(state) == 2 and \
                isinstance(state[0], tuple) and isinstance(state[1], NDArray) \
                and weight._data.dtype in (jnp.float16, jnp.bfloat16):
            state, master = state
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray) and master is None \
                and getattr(self, "lazy_update", False) \
                and grad._aux["indices"]._data.shape[0] \
                < weight._data.shape[0]:
            # lazy row update: touch only the rows named by the gradient
            # (reference lazy_update semantics — wd/momentum decay also
            # apply only to touched rows). An all-rows sparse grad (e.g.
            # post-allreduce writeback) takes the dense rule below: a full
            # gather+scatter would only add overhead.
            self._update_one_sparse(index, weight, grad, state, t, lr, wd)
            return
        fn = self._jitted()
        raw_state = tuple(s._data for s in state)
        clip = self.clip_gradient if self.clip_gradient is not None else 0.0
        if master is not None:
            new_master, new_state = fn(master._data,
                                       grad._data.astype(jnp.float32),
                                       lr, wd, t, self.rescale_grad, clip,
                                       raw_state)
            master._data = new_master
            weight._data = new_master.astype(weight._data.dtype)
        else:
            new_w, new_state = fn(weight._data, grad._data, lr, wd, t,
                                  self.rescale_grad, clip, raw_state)
            weight._data = new_w
        for s, ns in zip(state, new_state):
            s._data = ns

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.learning_rate})"


class _StatefulMixin:
    """States stored as a dict index->pytree of jax arrays owned by the
    Updater/Trainer; update() returns new states functionally."""


@register
class SGD(Optimizer):
    """SGD with momentum/nesterov-free path (reference optimizer/sgd.py;
    kernels src/operator/optimizer_op.cc sgd_update/sgd_mom_update)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        # reference sgd.py:95 lazy_update=False default; when opted in it
        # engages only when the gradient arrives row_sparse (Embedding
        # sparse_grad), skipping wd/momentum on untouched rows
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return self._zeros_state(weight, 1)

    def _rule(self):
        mom = self.momentum

        def rule(w, g, lr, wd, t, states):
            g = g + wd * w
            if mom == 0.0:
                return w - lr * g, states
            (m,) = states
            m = mom * m - lr * g
            return w + m, (m,)
        return rule


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference optimizer/nag.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return self._zeros_state(weight, 1)

    def _rule(self):
        mom = self.momentum

        def rule(w, g, lr, wd, t, states):
            g = g + wd * w
            (m,) = states
            m = mom * m + g
            return w - lr * (g + mom * m), (m,)
        return rule


@register
class Signum(Optimizer):
    """Sign SGD with momentum (reference optimizer/signum.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return self._zeros_state(weight, 1) if self.momentum != 0 else ()

    def _rule(self):
        mom, wd_lh = self.momentum, self.wd_lh

        def rule(w, g, lr, wd, t, states):
            if mom == 0.0:
                return w * (1 - lr * (wd + wd_lh)) - lr * jnp.sign(g), states
            (m,) = states
            m = mom * m - (1 - mom) * (g + wd * w)
            return w * (1 - lr * wd_lh) + lr * jnp.sign(m), (m,)
        return rule


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer/sgld.py)."""

    # jax.random.fold_in needs a SCALAR step count; vector ts from a
    # bucketed shard would break the noise key derivation
    elementwise_update = False

    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._keyidx = 0

    def create_state(self, index, weight):
        return ()

    def _rule(self):
        def rule(w, g, lr, wd, t, states):
            g = g + wd * w
            key = jax.random.fold_in(jax.random.PRNGKey(0x51D), t)
            noise = jax.random.normal(key, w.shape, w.dtype) * \
                jnp.sqrt(lr)
            return w - 0.5 * lr * g + noise, states
        return rule


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer/dcasgd.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.array(weight._data)))  # (mom, prev_weight)

    def _rule(self):
        mom, lam = self.momentum, self.lamda

        def rule(w, g, lr, wd, t, states):
            m, prev = states
            g = g + wd * w
            g = g + lam * g * g * (w - prev)
            m = mom * m - lr * g
            return w + m, (m, jnp.array(w))
        return rule


@register
class Adam(Optimizer):
    """Adam (reference optimizer/adam.py; kernel adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        # reference adam.py:86 lazy_update=False default; when opted in,
        # row_sparse grads touch only their rows (bias correction still
        # uses the global step t, as upstream)
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def rule(w, g, lr, wd, t, states):
            m, v = states
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            return w - lr * mhat / (jnp.sqrt(vhat) + eps), (m, v)
        return rule


@register
class AdamW(Optimizer):
    """Adam with DECOUPLED weight decay (reference contrib adamw_update,
    src/operator/contrib/adamw.cc): wd applies directly to the weight,
    outside the adaptive moments — the transformer-training default."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.correct_bias = correct_bias
        self.lazy_update = True  # elementwise rule: sparse rows safe

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        correct = self.correct_bias

        def rule(w, g, lr, wd, t, states):
            m, v = states
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            if correct:
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
            else:
                mhat, vhat = m, v
            upd = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            return w - lr * upd, (m, v)
        return rule


@register
class AdaBelief(Optimizer):
    """AdaBelief (belief in observed gradients)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def rule(w, g, lr, wd, t, states):
            m, s = states
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            s = b2 * s + (1 - b2) * (g - m) ** 2 + eps
            mhat = m / (1 - b1 ** t)
            shat = s / (1 - b2 ** t)
            return w - lr * mhat / (jnp.sqrt(shat) + eps), (m, s)
        return rule


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2 = self.beta1, self.beta2

        def rule(w, g, lr, wd, t, states):
            m, u = states
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            u = jnp.maximum(b2 * u, jnp.abs(g))
            return w - lr / (1 - b1 ** t) * m / (u + 1e-8), (m, u)
        return rule


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps, sd = self.beta1, self.beta2, self.epsilon, \
            self.schedule_decay

        def rule(w, g, lr, wd, t, states):
            m, v = states
            g = g + wd * w
            mu_t = b1 * (1 - 0.5 * 0.96 ** (t * sd))
            mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * sd))
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            ghat = g / (1 - mu_t)
            mhat = m / (1 - mu_t1)
            vhat = v / (1 - b2 ** t)
            mbar = (1 - mu_t) * ghat + mu_t1 * mhat
            return w - lr * mbar / (jnp.sqrt(vhat) + eps), (m, v)
        return rule


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon
        self.lazy_update = True  # elementwise rule: safe on sparse rows

    def create_state(self, index, weight):
        return self._zeros_state(weight, 1)

    def _rule(self):
        eps = self.epsilon

        def rule(w, g, lr, wd, t, states):
            (h,) = states
            g = g + wd * w
            h = h + g * g
            return w - lr * g / (jnp.sqrt(h) + eps), (h,)
        return rule


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with one shared learning-rate history per ROW of the
    parameter (reference optimizer/contrib.py:26): history accumulates
    mean(grad^2) over the non-leading axes. Weight decay is not
    supported, matching the reference."""

    elementwise_update = False  # row-mean reduction needs the full shape

    def __init__(self, learning_rate=0.01, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        if self.wd != 0.0:
            raise MXNetError("GroupAdaGrad does not support weight decay")
        self.epsilon = epsilon
        self.lazy_update = True  # row-wise rule: safe on sparse rows

    def create_state(self, index, weight):
        d = weight._data
        return (NDArray(jnp.zeros((d.shape[0],) + (1,) * (d.ndim - 1),
                                  d.dtype)),)

    def _rule(self):
        eps = self.epsilon

        def rule(w, g, lr, wd, t, states):
            (h,) = states
            axes = tuple(range(1, g.ndim))
            h = h + (jnp.mean(g * g, axis=axes, keepdims=True)
                     if axes else g * g)
            return w - lr * g / (jnp.sqrt(h) + eps), (h,)
        return rule


@register
class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        rho, eps = self.rho, self.epsilon

        def rule(w, g, lr, wd, t, states):
            acc_g, acc_d = states
            g = g + wd * w
            acc_g = rho * acc_g + (1 - rho) * g * g
            d = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
            acc_d = rho * acc_d + (1 - rho) * d * d
            return w - lr * d, (acc_g, acc_d)
        return rule


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum = rho, momentum
        self.epsilon, self.centered = epsilon, centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return self._zeros_state(weight, 3)  # n, g_avg, delta
        return self._zeros_state(weight, 2)  # n, delta

    def _rule(self):
        rho, mom, eps = self.rho, self.momentum, self.epsilon
        centered, cw = self.centered, self.clip_weights

        def rule(w, g, lr, wd, t, states):
            g = g + wd * w
            if centered:
                n, gavg, delta = states
                n = rho * n + (1 - rho) * g * g
                gavg = rho * gavg + (1 - rho) * g
                delta = mom * delta - lr * g / \
                    (jnp.sqrt(n - gavg * gavg + eps))
                w = w + delta
                new_states = (n, gavg, delta)
            else:
                n, delta = states
                n = rho * n + (1 - rho) * g * g
                delta = mom * delta - lr * g / jnp.sqrt(n + eps)
                w = w + delta
                new_states = (n, delta)
            if cw:
                w = jnp.clip(w, -cw, cw)
            return w, new_states
        return rule


@register
class Ftrl(Optimizer):
    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        l1, beta = self.lamda1, self.beta

        def rule(w, g, lr, wd, t, states):
            z, n = states
            g = g + wd * w
            sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
            z = z + g - sigma * w
            n = n + g * g
            w = jnp.where(
                jnp.abs(z) > l1,
                -(z - jnp.sign(z) * l1) / ((beta + jnp.sqrt(n)) / lr),
                jnp.zeros_like(w))
            return w, (z, n)
        return rule


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return self._zeros_state(weight, 3)  # d, v, z

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def rule(w, g, lr, wd, t, states):
            d, v, z = states
            g = g + wd * w
            v = b2 * v + (1 - b2) * g * g
            d_t = (1 - b1 ** t) / lr * \
                (jnp.sqrt(v / (1 - b2 ** t)) + eps)
            sigma = d_t - b1 * d
            z = b1 * z + (1 - b1) * g - sigma * w
            w = -z / d_t
            return w, (d_t, v, z)
        return rule


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference optimizer/lars.py)."""

    elementwise_update = False  # trust ratio needs the full-layer norms

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        return self._zeros_state(weight, 1)

    def _rule(self):
        mom, eta, eps = self.momentum, self.eta, self.epsilon

        def rule(w, g, lr, wd, t, states):
            (m,) = states
            wnorm = jnp.sqrt(jnp.sum(w * w))
            gnorm = jnp.sqrt(jnp.sum(g * g))
            trust = jnp.where(
                (wnorm > 0) & (gnorm > 0),
                eta * wnorm / (gnorm + wd * wnorm + eps), 1.0)
            g = g + wd * w
            m = mom * m + trust * lr * g
            return w - m, (m,)
        return rule


@register
class LAMB(Optimizer):
    """Layer-wise Adam for large-batch (reference optimizer/lamb.py)."""

    elementwise_update = False  # trust ratio needs the full-layer norms

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        lo, hi, bc = self.lower_bound, self.upper_bound, self.bias_correction

        def rule(w, g, lr, wd, t, states):
            m, v = states
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            if bc:
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
            else:
                mhat, vhat = m, v
            r = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            wnorm = jnp.sqrt(jnp.sum(w * w))
            rnorm = jnp.sqrt(jnp.sum(r * r))
            if lo is not None:
                wnorm = jnp.maximum(wnorm, lo)
            if hi is not None:
                wnorm = jnp.minimum(wnorm, hi)
            trust = jnp.where((wnorm > 0) & (rnorm > 0), wnorm / rnorm, 1.0)
            return w - lr * trust * r, (m, v)
        return rule


@register
class LANS(Optimizer):
    """LAMB with normalized gradients (reference optimizer/lans.py)."""

    elementwise_update = False  # trust ratio needs the full-layer norms

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return self._zeros_state(weight, 2)

    def _rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def rule(w, g, lr, wd, t, states):
            m, v = states
            gnorm = jnp.sqrt(jnp.sum(g * g))
            g = g / jnp.maximum(gnorm, 1e-12)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            r1 = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            r2 = g / (jnp.sqrt(vhat) + eps) + wd * w
            wnorm = jnp.sqrt(jnp.sum(w * w))

            def ratio(r):
                rn = jnp.sqrt(jnp.sum(r * r))
                return jnp.where((wnorm > 0) & (rn > 0), wnorm / rn, 1.0)
            w = w - lr * (b1 * ratio(r1) * r1 + (1 - b1) * ratio(r2) * r2)
            return w, (m, v)
        return rule


class Updater:
    """Applies an optimizer to indexed weights, owning the state dict
    (reference optimizer/updater.py — the kvstore-side updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}

    def __call__(self, index, grad, weight):
        indices = index if isinstance(index, (list, tuple)) else [index]
        grads = grad if isinstance(grad, (list, tuple)) else [grad]
        weights = weight if isinstance(weight, (list, tuple)) else [weight]
        for i, w in zip(indices, weights):
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
        if len(indices) > 1:
            # multi-tensor fused update: one XLA dispatch for all params
            self.optimizer.update(list(indices), list(weights), list(grads),
                                  [self.states[i] for i in indices])
        else:
            self.optimizer._update_one(indices[0], weights[0], grads[0],
                                       self.states[indices[0]])

    def get_states(self, dump_optimizer=False):
        """Reference optimizer/updater.py: pickles (states, optimizer) when
        dump_optimizer so num_update / index counts survive a restart."""
        import pickle
        host = {k: jax.tree_util.tree_map(
                    lambda s: onp.asarray(s._data), v,
                    is_leaf=lambda s: isinstance(s, NDArray))
                for k, v in self.states.items()}
        if dump_optimizer:
            meta = dict(num_update=self.optimizer.num_update,
                        index_update_count=dict(
                            self.optimizer._index_update_count))
            return pickle.dumps((host, type(self.optimizer).__name__, meta))
        return pickle.dumps(host)

    def set_states(self, states_bytes):
        import pickle
        loaded = pickle.loads(states_bytes)
        if isinstance(loaded, tuple):
            loaded, _opt_name, meta = loaded
            self.optimizer.num_update = meta["num_update"]
            self.optimizer._index_update_count.update(
                meta["index_update_count"])
        self.states = {k: jax.tree_util.tree_map(
                           lambda a: NDArray(jnp.asarray(a)), v)
                       for k, v in loaded.items()}


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
