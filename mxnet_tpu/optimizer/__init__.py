"""Optimizer package (reference: python/mxnet/optimizer/ — 19 classes)."""
from .optimizer import *  # noqa: F401,F403
from .optimizer import __all__  # noqa: F401
from . import optimizer
