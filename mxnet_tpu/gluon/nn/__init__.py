"""Gluon neural-network layers (reference: python/mxnet/gluon/nn/)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .moe import *  # noqa: F401,F403
from . import basic_layers, conv_layers, transformer, moe
from .basic_layers import __all__ as _b
from .conv_layers import __all__ as _c
from .transformer import __all__ as _t
from .moe import __all__ as _m

__all__ = list(_b) + list(_c) + list(_t) + list(_m)
