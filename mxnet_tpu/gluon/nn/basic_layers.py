"""Basic Gluon layers (reference: python/mxnet/gluon/nn/basic_layers.py).

Layers are HybridBlocks whose forward is plain imperative NDArray code; under
``hybridize()`` the same code traces into one XLA computation. Shape
inference is inline: a layer with unknown input dims completes its parameter
shapes on first forward (replacing the reference's deferred-init machinery).
"""
from __future__ import annotations

from typing import Optional

import numpy as onp

from ... import autograd
from ...base import MXNetError
from ...ndarray import ops as F
from ...ndarray import nn_ops as FNN
from ...ndarray.ndarray import NDArray
from ...ndarray.random import next_key
from ...ops import nn as K
from ...ops.registry import invoke_raw
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "BatchNormReLU", "SyncBatchNorm", "LayerNorm", "GroupNorm",
           "InstanceNorm", "Flatten", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "GELU", "Swish", "SiLU", "Lambda", "HybridLambda",
           "Identity", "Concatenate", "HybridConcatenate"]


class Sequential(Block):
    """Sequentially-stacked blocks (reference basic_layers.py Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(x W^T + b) (reference Dense;
    the op is reference FullyConnected, src/operator/nn/fully_connected.cc).
    Weight layout (units, in_units) matches the reference for checkpoint
    compat; XLA folds the transpose into the MXU matmul."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer)
        self.bias = Parameter("bias", shape=(units,), dtype=dtype,
                              init=bias_initializer) if use_bias else None

    def _infer(self, x):
        if self.weight._data is None:
            in_units = int(onp.prod(x.shape[1:])) if self._flatten \
                else x.shape[-1]
            self.weight.shape = (self._units, in_units)
            if self.weight._deferred_init_args is not None:
                self.weight._finish_deferred_init()
            if self.bias is not None and self.bias._deferred_init_args is not None:
                self.bias._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        out = F.FullyConnected(x, self.weight.data(),
                               None if self.bias is None else self.bias.data(),
                               num_hidden=self._units,
                               no_bias=self.bias is None,
                               flatten=self._flatten)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Embedding(HybridBlock):
    """Embedding lookup (reference gluon Embedding). ``sparse_grad=True``
    gives the weight a row_sparse gradient: backward produces only the
    touched rows and lazy optimizers (SGD/Adam/AdaGrad) update only those
    rows — the O(rows) path for large vocabularies. Requires the eager
    (non-hybridized) path; inside a jit trace gradients are dense."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def forward(self, x):
        return F.Embedding(x, self.weight.data(), input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class BatchNorm(HybridBlock):
    """Batch normalization (reference BatchNorm layer + batch_norm op).

    Running stats update functionally: the parameter handle is rebound, which
    the hybridize trace captures as an extra output and writes back after the
    compiled step (see block.py _build_cache)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        ch = in_channels
        self.gamma = Parameter("gamma", shape=(ch,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(ch,), init=beta_initializer,
                              grad_req="write" if center else "null")
        self.running_mean = Parameter("running_mean", shape=(ch,),
                                      init=running_mean_initializer,
                                      grad_req="null")
        self.running_var = Parameter("running_var", shape=(ch,),
                                     init=running_variance_initializer,
                                     grad_req="null")

    def _infer(self, x):
        if self.gamma._data is None:
            ch = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean,
                      self.running_var):
                p.shape = (ch,)
                if p._deferred_init_args is not None:
                    p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        if self._axis != 1:
            x = x.swapaxes(1, self._axis)
        g, b = self.gamma.data(), self.beta.data()
        mm, mv = self.running_mean.data(), self.running_var.data()
        training = autograd.is_training() and not self._use_global_stats
        if not training:
            out = invoke_raw(
                "batch_norm",
                lambda xx, gg, bb, m, v: K.batch_norm_infer(
                    xx, gg, bb, m, v, self._eps),
                [x, g, b, mm, mv])
        else:
            res = invoke_raw(
                "batch_norm",
                lambda xx, gg, bb: K.batch_norm_train(xx, gg, bb, self._eps),
                [x, g, b], n_outputs=3)
            out, bmean, bvar = res
            mom = self._momentum
            with autograd.pause():
                self.running_mean._data = mom * mm + (1 - mom) * bmean
                self.running_var._data = mom * mv + (1 - mom) * bvar
        if self._axis != 1:
            out = out.swapaxes(1, self._axis)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference contrib SyncBatchNorm): under a
    sharded data-parallel step the batch axis is a mesh axis and XLA computes
    global batch stats via psum when the input is sharded; single-device
    behavior equals BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class BatchNormReLU(BatchNorm):
    """BatchNorm with a fused trailing ReLU (reference gluon/nn
    basic_layers.py BatchNormReLU, backed by the _npx_batch_norm+relu
    kernel there). Here the relu composes onto the BN output and XLA
    fuses the pair into one kernel."""

    def forward(self, x):
        from ... import ndarray as F
        return F.relu(super().forward(x))


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              grad_req="write" if center else "null")

    def _infer(self, x):
        if self.gamma._data is None:
            ch = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (ch,)
                if p._deferred_init_args is not None:
                    p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        return FNN.LayerNorm(x, self.gamma.data(), self.beta.data(),
                             axis=self._axis, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._ngroups = num_groups
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              grad_req="write" if center else "null")

    def _infer(self, x):
        if self.gamma._data is None:
            ch = x.shape[1]
            for p in (self.gamma, self.beta):
                p.shape = (ch,)
                if p._deferred_init_args is not None:
                    p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        return FNN.GroupNorm(x, self.gamma.data(), self.beta.data(),
                             num_groups=self._ngroups, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              grad_req="write" if center else "null")

    def _infer(self, x):
        if self.gamma._data is None:
            ch = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (ch,)
                if p._deferred_init_args is not None:
                    p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        if self._axis != 1:
            x = x.swapaxes(1, self._axis)
        out = FNN.InstanceNorm(x, self.gamma.data(), self.beta.data(),
                               eps=self._eps)
        if self._axis != 1:
            out = out.swapaxes(1, self._axis)
        return out


class Flatten(HybridBlock):
    def forward(self, x):
        return x.flatten()

    def __repr__(self):
        return "Flatten"


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def forward(self, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="constant", in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as I
        init = I.Constant(0.25) if alpha_initializer == "constant" \
            else alpha_initializer
        self.alpha = Parameter("alpha", shape=(in_channels,), init=init)

    def forward(self, x):
        return F.LeakyReLU(x, act_type="prelu", gamma=self.alpha.data())


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def forward(self, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x):
        return x * F.sigmoid(self._beta * x)


SiLU = Swish


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (reference
    contrib Concurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self._axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self._axis)
