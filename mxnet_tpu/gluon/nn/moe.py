"""Mixture-of-Experts Gluon layer.

No reference analog (the reference has no MoE — SURVEY §2.3 lists expert
parallelism as absent); TPU-native extension backed by ``ops/moe.py``
(GShard/Switch-style capacity-bounded router + batched expert einsums, with
an expert-parallel all-to-all path for mesh execution).
"""
from __future__ import annotations

from ...ndarray.ndarray import NDArray
from ...ops.registry import invoke_raw
from ...ops import moe as moe_ops
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["MoE"]


class MoE(HybridBlock):
    """Sparse expert FFN: ``out, aux = moe(x)``.

    x (..., units) is flattened to tokens; each token routes to ``top_k`` of
    ``num_experts`` expert FFNs (units -> hidden -> units). ``aux`` is the
    load-balance loss (≈1 when balanced) to add to the training objective.
    For expert-parallel execution shard the expert dimension of
    ``w1/w2`` over an 'ep' mesh axis and call ``ops.moe.moe_ffn`` with
    ``axis_name`` inside shard_map (see __graft_entry__ dryrun)."""

    def __init__(self, units, hidden, num_experts, top_k=2,
                 capacity_factor=1.25, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._e, self._k = num_experts, top_k
        self._cf = capacity_factor
        self.gate = Parameter("gate", shape=(units, num_experts),
                              dtype=dtype)
        self.w1 = Parameter("w1", shape=(num_experts, units, hidden),
                            dtype=dtype)
        self.w2 = Parameter("w2", shape=(num_experts, hidden, units),
                            dtype=dtype)

    def forward(self, x):
        units = self.w1.shape[1]
        shape = x.shape

        def fn(xd, gw, w1, w2):
            tokens = xd.reshape(-1, units)
            out, aux = moe_ops.moe_ffn(tokens, gw, w1, w2, top_k=self._k,
                                       capacity_factor=self._cf)
            return out.reshape(shape), aux

        out, aux = invoke_raw(
            "moe_ffn", fn,
            [x if isinstance(x, NDArray) else NDArray(x),
             self.gate.data(), self.w1.data(), self.w2.data()],
            n_outputs=2)
        return out, aux
