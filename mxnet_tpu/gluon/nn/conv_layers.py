"""Convolution / pooling Gluon layers (reference: gluon/nn/conv_layers.py).

All conv layers carry NC+spatial ("NCHW"-family) layouts like the reference;
the kernels lower to a single `lax.conv_general_dilated` (ops/nn.py) which
XLA tiles onto the MXU.
"""
from __future__ import annotations

from typing import Optional

from ...base import MXNetError
from ...ndarray import nn_ops as FNN
from ...ndarray import ops as F
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 ndim=2, transpose=False, output_padding=0, dtype="float32",
                 **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._ndim = ndim
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._transpose = transpose
        self._adj = _tup(output_padding, ndim)
        self._activation = activation
        if layout is not None and not layout.startswith("NC"):
            raise MXNetError(f"only NC-leading layouts supported, got {layout}")
        if transpose:
            wshape = (in_channels, channels // groups) + self._kernel
        else:
            wshape = (channels, in_channels // groups if in_channels else 0) \
                + self._kernel
        self.weight = Parameter("weight", shape=wshape, dtype=dtype,
                                init=weight_initializer)
        self.bias = Parameter("bias", shape=(channels,), dtype=dtype,
                              init=bias_initializer) if use_bias else None

    def _infer(self, x):
        if self.weight._data is None:
            in_ch = x.shape[1]
            if self._transpose:
                self.weight.shape = (in_ch, self._channels // self._groups) \
                    + self._kernel
            else:
                self.weight.shape = (self._channels, in_ch // self._groups) \
                    + self._kernel
            if self.weight._deferred_init_args is not None:
                self.weight._finish_deferred_init()
            if self.bias is not None and \
                    self.bias._deferred_init_args is not None:
                self.bias._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        b = None if self.bias is None else self.bias.data()
        if self._transpose:
            out = FNN.Deconvolution(
                x, self.weight.data(), b, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation,
                pad=self._padding, adj=self._adj, num_filter=self._channels,
                num_group=self._groups, no_bias=b is None)
        else:
            out = FNN.Convolution(
                x, self.weight.data(), b, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation,
                pad=self._padding, num_filter=self._channels,
                num_group=self._groups, no_bias=b is None)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=3, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=1,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=2,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, ndim=3,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, pool_type, ndim,
                 global_pool=False, count_include_pad=True, ceil_mode=False,
                 layout=None, **kwargs):
        super().__init__(**kwargs)
        self._kernel = _tup(pool_size, ndim)
        self._strides = _tup(strides if strides is not None else pool_size,
                             ndim)
        self._padding = _tup(padding, ndim)
        self._pool_type = pool_type
        self._global = global_pool
        self._cip = count_include_pad
        self._ceil = ceil_mode

    def forward(self, x):
        return FNN.Pooling(x, kernel=self._kernel, pool_type=self._pool_type,
                           stride=self._strides, pad=self._padding,
                           global_pool=self._global,
                           count_include_pad=self._cip,
                           ceil_mode=self._ceil)


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 1,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 2,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 3,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 1,
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 2,
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 3,
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, **kwargs)


class _GlobalPool(HybridBlock):
    def __init__(self, pool_type, **kwargs):
        super().__init__(**kwargs)
        self._pool_type = pool_type

    def forward(self, x):
        return FNN.Pooling(x, pool_type=self._pool_type, global_pool=True)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("max", **kwargs)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("max", **kwargs)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("max", **kwargs)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("avg", **kwargs)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("avg", **kwargs)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("avg", **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        self._padding = padding

    def forward(self, x):
        p = self._padding
        pw = (0, 0, 0, 0, p, p, p, p) if isinstance(p, int) else p
        return F.pad(x, mode="reflect", pad_width=pw)
