"""Transformer layers: MultiHeadAttention, FFN, encoder stack.

Reference parity note: MXNet 2.0-dev keeps attention out-of-tree (gluon-nlp
composed it from batch_dot + softmax — no fused kernel, SURVEY.md §2.3/§5).
Here attention is a first-class fused op (ops/attention.py: Pallas flash
kernel on TPU, ring attention for context parallelism), and these layers are
the Gluon-API building blocks over it, used by model_zoo.bert.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray import ops as F
from ...ndarray.ndarray import NDArray
from ...ops import attention as ATT
from ...ops.registry import invoke_raw
from ..block import HybridBlock
from ..parameter import Parameter
from .basic_layers import Dense, Dropout, LayerNorm

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "TransformerEncoder"]


def _masked_attention(q, k, v, mask, sm_scale, causal=False,
                      valid_length=None):
    """Arbitrary-additive-mask attention — delegates to the shared oracle
    impl in ops/attention.py (unfused; XLA fuses the softmax). Only used
    for masks that aren't expressible as valid_length — padding alone
    should pass ``valid_length`` and stay on the flash path. When both are
    given, padding is folded into the additive mask here."""
    if valid_length is not None:
        sk = k.shape[2]
        keep = jnp.arange(sk)[None, :] < valid_length[:, None]
        mask = mask + jnp.where(keep, 0.0, ATT._NEG_INF)[:, None, None, :]
    return ATT.attention_reference(q, k, v, causal=causal,
                                   sm_scale=sm_scale, mask=mask)


class MultiHeadAttention(HybridBlock):
    """Multi-head attention over (batch, seq, units) inputs.

    ``forward(q, k=None, v=None, mask=None, valid_length=None)``:
    self-attention when k/v are omitted. ``valid_length`` (B,) masks padded
    keys and stays on the fused flash path (blockwise, O(S·block) memory).
    ``mask`` is an arbitrary additive float mask broadcastable to
    (batch, heads, seq_q, seq_k) (0 keep / -inf drop) — that path is
    unfused; prefer valid_length for plain padding.
    """

    def __init__(self, units: int, num_heads: int, dropout: float = 0.0,
                 use_bias: bool = True, causal: bool = False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self.query_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=units)
        self.key_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        self.value_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=units)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        self.dropout = Dropout(dropout)

    def _split(self, x):
        b, s, _ = x.shape
        return F.transpose(
            F.reshape(x, (b, s, self._num_heads,
                          self._units // self._num_heads)),
            axes=(0, 2, 1, 3))

    def forward(self, q, k=None, v=None, mask=None, valid_length=None):
        k = q if k is None else k
        v = k if v is None else v
        qh = self._split(self.query_proj(q))
        kh = self._split(self.key_proj(k))
        vh = self._split(self.value_proj(v))
        d = self._units // self._num_heads
        scale = 1.0 / math.sqrt(d)
        if mask is not None:
            inputs = [qh, kh, vh, mask if isinstance(mask, NDArray)
                      else NDArray(jnp.asarray(mask))]
            if valid_length is not None:
                vl_data = valid_length._data \
                    if isinstance(valid_length, NDArray) \
                    else jnp.asarray(valid_length)
                inputs.append(NDArray(jnp.asarray(vl_data, jnp.float32)))

                def fn(q_, k_, v_, m_, vl_):
                    return _masked_attention(q_, k_, v_, m_, scale,
                                             causal=self._causal,
                                             valid_length=vl_)
            else:
                fn = functools.partial(_masked_attention, sm_scale=scale,
                                       causal=self._causal)
            out = invoke_raw("masked_attention", fn, inputs)
        elif valid_length is not None:
            def fn(q_, k_, v_, vl_):
                return ATT.flash_attention(q_, k_, v_, causal=self._causal,
                                           sm_scale=scale, valid_length=vl_)
            vl_data = valid_length._data if isinstance(valid_length, NDArray) \
                else jnp.asarray(valid_length)
            # float32: integer tape inputs would get float0 cotangents
            vl = NDArray(jnp.asarray(vl_data, jnp.float32))
            out = invoke_raw("flash_attention_vl", fn, [qh, kh, vh, vl])
        else:
            fn = functools.partial(ATT.flash_attention, causal=self._causal,
                                   sm_scale=scale)
            out = invoke_raw("flash_attention", fn, [qh, kh, vh])
        b, _, s, _ = out.shape
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        (b, s, self._units))
        return self.dropout(self.out_proj(out))


class PositionwiseFFN(HybridBlock):
    """Transformer FFN: dense → activation → dense (+ dropout).

    With ``activation='gelu'`` the first dense's bias add and the GELU
    fuse into one Pallas kernel when the MXNET_PALLAS gate selects it
    (ops/kernels/norm.py ``bias_gelu``; the matmul stays on the MXU) —
    XLA otherwise materializes the (tokens, hidden) pre-activation to
    HBM between the two. Identical math: gelu((x W^T) + b), exact erf
    form, same parameters."""

    def __init__(self, units: int, hidden_size: int, dropout: float = 0.0,
                 activation: str = "gelu", **kwargs):
        super().__init__(**kwargs)
        self.ffn_1 = Dense(hidden_size, flatten=False, in_units=units)
        self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size)
        self._activation = activation
        self.dropout = Dropout(dropout)

    def _bias_gelu_path(self, x):
        """'interpret'/'pallas' when the fused bias-GELU kernel should
        take this call, else None (reference Dense→Activation)."""
        if self._activation != "gelu" or self.ffn_1.bias is None:
            return None
        from ...ops.kernels import dispatch as _kdispatch
        from ...ops.kernels import norm as _knorm
        why = _knorm.norm_supported(x, self.ffn_1.weight.shape[0])
        path, _ = _kdispatch("bias_gelu", supported=why is None,
                             reason=why)
        return None if path == "xla" else path

    def forward(self, x):
        path = self._bias_gelu_path(x)
        if path is not None:
            from ...ops.kernels.norm import bias_gelu
            interpret = path == "interpret"

            def fn(x_, w_, b_):
                return bias_gelu(x_ @ w_.T, b_, interpret=interpret)

            h = invoke_raw("bias_gelu_dense", fn,
                           [x, self.ffn_1.weight.data(),
                            self.ffn_1.bias.data()])
        else:
            h = F.Activation(self.ffn_1(x), act_type=self._activation)
        return self.dropout(self.ffn_2(h))


class TransformerEncoderCell(HybridBlock):
    """Post-LN (BERT-style) or pre-LN transformer encoder layer."""

    def __init__(self, units: int, hidden_size: int, num_heads: int,
                 dropout: float = 0.0, pre_norm: bool = False,
                 activation: str = "gelu", causal: bool = False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        self.attention = MultiHeadAttention(units, num_heads, dropout=dropout,
                                            causal=causal)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                   activation=activation)
        self.ln_1 = LayerNorm(in_channels=units)
        self.ln_2 = LayerNorm(in_channels=units)

    def forward(self, x, mask=None, valid_length=None):
        # MultiHeadAttention/PositionwiseFFN already apply output dropout —
        # no extra dropout here (rate would compound past the configured p).
        if self._pre_norm:
            x = x + self.attention(self.ln_1(x), mask=mask,
                                   valid_length=valid_length)
            return x + self.ffn(self.ln_2(x))
        x = self.ln_1(x + self.attention(x, mask=mask,
                                         valid_length=valid_length))
        return self.ln_2(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells."""

    def __init__(self, num_layers: int, units: int, hidden_size: int,
                 num_heads: int, dropout: float = 0.0, pre_norm: bool = False,
                 activation: str = "gelu", causal: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.layers = []
        for i in range(num_layers):
            cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                          dropout=dropout, pre_norm=pre_norm,
                                          activation=activation, causal=causal)
            setattr(self, f"layer{i}", cell)
            self.layers.append(cell)

    def forward(self, x, mask=None, valid_length=None):
        for cell in self.layers:
            x = cell(x, mask=mask, valid_length=valid_length)
        return x
