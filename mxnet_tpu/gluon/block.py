"""Gluon Block / HybridBlock.

Reference analog: python/mxnet/gluon/block.py (Block :201, HybridBlock :859;
_build_cache :993 traces forward under deferred compute into a Symbol and
wraps it in a C++ CachedOp; __call__ :1384 routes to _call_cached_op :1095).

TPU-native re-design: ``hybridize()`` makes the whole forward ONE XLA
computation. ``_CachedOp`` here traces the block's imperative forward with
``jax.jit`` — NDArray is a jax pytree node, so the same Python forward code
runs both eagerly and under trace. Under ``autograd.record`` the jitted
callable becomes a single tape node, so backward is also one fused XLA
computation (the reference needed bulking + static_alloc to approximate this;
XLA gives it natively, which is the core perf story of the rebuild).

Mutable layer state (BatchNorm running stats) is handled functionally: params
rebound during tracing are detected and returned as extra outputs, then
written back after each call — the jit-compatible version of the reference's
aux-state mutation.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as onp

import jax

from .. import _tape, autograd
from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray import utils as nd_utils
from ..ndarray.ndarray import NDArray
from ..ndarray.random import next_key, push_trace_key, pop_trace_key
from ..ops.registry import invoke_raw


def _wrap_nd(x):
    """jax array (or NDArray) -> NDArray view for op-hook callbacks."""
    return x if isinstance(x, NDArray) else NDArray(x)
from .parameter import Parameter, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _HookHandle:
    """Detachable hook registration (reference gluon/utils.py HookHandle)."""

    def __init__(self, hooks_list, hook):
        self._list = hooks_list
        self._hook = hook

    def detach(self):
        if self._hook in self._list:
            self._list.remove(self._hook)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()


class _TracedSentinel:
    """Marks a traced-leaf position inside a cached op's static_spec."""

    def __repr__(self):
        return "<traced>"


_TRACED = _TracedSentinel()


class ParamBinding:
    """Functional parameter binding for whole-graph traces.

    Shared by the CachedOp trace (``_build_cache``) and the fused train
    step (``gluon.fused_step``): binds raw jax arrays into Parameters for
    the duration of an imperative forward running under trace, then on
    exit captures functional rebinds (BatchNorm running stats replace
    ``Parameter._data`` with a new handle) and restores the original
    handles. ``grad_req='null'`` params are bound behind
    ``lax.stop_gradient`` so reverse-mode prunes their dead gradients.

    After ``__exit__``:
      - ``state``     tuple over params of the raw (possibly updated) array
      - ``state_idx`` indices of params whose handle was rebound in forward
    """

    __slots__ = ("params", "datas", "state", "state_idx", "_orig",
                 "_bound_ids")

    def __init__(self, params, datas):
        self.params = list(params)
        self.datas = list(datas)
        self.state = None
        self.state_idx = None

    def __enter__(self):
        self._orig = [p._data for p in self.params]
        self._bound_ids = []
        for p, d in zip(self.params, self.datas):
            nd = NDArray(jax.lax.stop_gradient(d)
                         if p.grad_req == "null" else d)
            p._data = nd
            self._bound_ids.append(id(nd))
        return self

    def __exit__(self, *exc):
        state, idx = [], []
        for i, p in enumerate(self.params):
            cur = p._data
            state.append(cur._data if isinstance(cur, NDArray) else cur)
            if id(cur) != self._bound_ids[i]:
                idx.append(i)
        self.state = tuple(state)
        self.state_idx = idx
        for p, o in zip(self.params, self._orig):
            p._data = o
        return False


def _in_trace(args) -> bool:
    """True when any input is a jax tracer — i.e. we are already inside an
    enclosing jit trace and must inline rather than nest cached ops."""
    for leaf in jax.tree_util.tree_leaves(args):
        if isinstance(leaf, jax.core.Tracer):
            return True
    return False


class _ParamDict(dict):
    """Dict of name->Parameter with reference ParameterDict conveniences."""

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename):
        nd_utils.save(filename, {k: v.data() for k, v in self.items()})

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False):
        loaded = nd_utils.load(filename)
        for k, v in self.items():
            if k in loaded:
                v.set_data(loaded[k])
            elif not allow_missing:
                raise MXNetError(f"parameter {k} missing in file {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self)
            if extra:
                raise MXNetError(f"file {filename} has extra params {extra}")


class Block:
    """Base class for all layers/models (reference gluon/block.py:201)."""

    def __init__(self, prefix: Optional[str] = None, params=None):
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List[Callable] = []
        self._forward_pre_hooks: List[Callable] = []
        self._op_hooks: List[Callable] = []  # register_op_hook wrappers
        self._op_hook_active = False
        self._prefix = prefix or ""
        self._name = type(self).__name__.lower()

    # ---------------- attribute registration ----------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", OrderedDict())[name] = value
        elif isinstance(value, Parameter):
            self.__dict__.setdefault("_reg_params", {})[name] = value
            if value._name in ("weight", "bias", "const", ""):
                value._name = name
        super().__setattr__(name, value)

    @property
    def name(self) -> str:
        return self._name

    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def params(self) -> Dict[str, Parameter]:
        return dict(self._reg_params)

    def name_scope(self):
        """1.x compat no-op scope (naming is structural in 2.0)."""
        import contextlib
        return contextlib.nullcontext(self)

    def register_child(self, block: "Block", name: Optional[str] = None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return _HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return _HookHandle(self._forward_pre_hooks, hook)

    # ---------------- parameter management ----------------
    def collect_params(self, select: Optional[str] = None) -> _ParamDict:
        """Structural-path-keyed parameter dict (reference block.py
        collect_params; 2.0 keys are 'child.param' paths)."""
        out = _ParamDict()
        self._collect_params_into(out, "")
        if select is not None:
            import re
            pat = re.compile(select.replace(".*", "@@").replace("*", ".*")
                             .replace("@@", ".*"))
            out = _ParamDict({k: v for k, v in out.items()
                              if pat.search(k) or pat.search(v.name)})
        return out

    def _collect_params_into(self, out: _ParamDict, prefix: str):
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            child._collect_params_into(out, f"{prefix}{cname}.")

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx,
                                         force_reinit=force_reinit)

    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        self._on_cast(dtype)

    def _on_cast(self, dtype):
        for child in self._children.values():
            child._on_cast(dtype)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ---------------- persistence ----------------
    def save_parameters(self, filename: str, deduplicate: bool = False):
        """Reference block.py:339 — structural-key param file."""
        params = self.collect_params()
        nd_utils.save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename: str, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Reference block.py:375."""
        loaded = nd_utils.load(filename)
        params = self.collect_params()
        for k, v in params.items():
            if k in loaded:
                arr = loaded[k]
                if cast_dtype and v._data is not None:
                    arr = arr.astype(v._data._data.dtype)
                v.set_data(arr)
            elif not allow_missing:
                raise MXNetError(f"parameter {k} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"{filename} contains extra parameters {extra}")

    def load_dict(self, param_dict, ctx=None, allow_missing=False,
                  ignore_extra=False, cast_dtype=False,
                  dtype_source="current"):
        """Load parameter values from a dict of name -> NDArray
        (reference block.py:430; 'arg:'/'aux:' key prefixes from 1.x
        save_checkpoint files are stripped). With ``cast_dtype``,
        ``dtype_source='current'`` casts incoming arrays to each
        parameter's dtype and ``'saved'`` re-types the parameter to the
        checkpoint's dtype."""
        if dtype_source not in ("current", "saved"):
            raise MXNetError("dtype_source must be 'current' or 'saved', "
                             f"got {dtype_source!r}")
        loaded = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                  for k, v in param_dict.items()}
        params = self.collect_params()
        for k, v in params.items():
            if k in loaded:
                arr = loaded[k]
                if cast_dtype and dtype_source == "saved" and \
                        v._data is not None:
                    v.cast(arr._data.dtype)
                v.set_data(arr)
            elif not allow_missing:
                raise MXNetError(
                    f"Parameter '{k}' is missing in param_dict. Set "
                    "allow_missing=True to ignore missing parameters.")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(
                    f"param_dict contains extra parameters {extra}; set "
                    "ignore_extra=True to ignore them.")

    def setattr(self, name, value):
        """Set an attribute on ALL Parameters, e.g.
        ``model.setattr('grad_req', 'null')`` (reference block.py:630)."""
        for p in self.collect_params().values():
            setattr(p, name, value)

    def share_parameters(self, shared):
        """Tie this block's Parameters to those in ``shared`` (a dict
        from another block's ``collect_params()``) by structured name:
        the Parameter OBJECTS are shared, so later loads into either
        block reflect in both (reference block.py:653)."""
        if shared is None:
            return self
        if not isinstance(shared, dict):
            raise ValueError("'shared' should be a dict of Parameters, "
                             f"got {type(shared)}")

        def walk(block, prefix):
            for name in list(block._reg_params):
                full = prefix + name
                if full in shared:
                    block._reg_params[name] = shared[full]
                    setattr(block, name, shared[full])
            for cname, child in block._children.items():
                walk(child, f"{prefix}{cname}.")
        walk(self, "")
        return self

    def register_op_hook(self, callback, monitor_all=False):
        """Install a monitor over every operator executed inside this
        block's forward: ``callback(tensor_name, op_name, NDArray)`` for
        each output (and each input when ``monitor_all``) — reference
        block.py:730, built here on the invoke-funnel wrapper stack the
        profiler/AMP/inspector use.

        Values are always CONCRETE: outside ``autograd.record()`` they
        come from the invoke wrapper; under recording the kernel runs
        inside a vjp trace (tracer values), so delivery moves to the
        tape's post-vjp output check, which sees the evaluated outputs
        (inputs are then not individually reported). Inside a
        hybridized/jitted cache there is no imperative dispatch to
        observe — hooks monitor eager execution, like the reference's
        executor monitor."""
        from ..ops import registry as _op_registry
        owner = self

        def deliver_outs(name, outs):
            for i, o in enumerate(outs):
                if hasattr(o, "shape"):
                    callback(f"{name}_output{i}" if len(outs) > 1
                             else f"{name}_output", name, _wrap_nd(o))

        def wrapper(name, fn):
            def monitored(*args, **kwargs):
                if not getattr(owner, "_op_hook_active", False) or \
                        _in_trace(args):
                    return fn(*args, **kwargs)
                if monitor_all:
                    for i, a in enumerate(args):
                        if hasattr(a, "shape"):
                            callback(f"{name}_input{i}", name,
                                     _wrap_nd(a))
                out = fn(*args, **kwargs)
                deliver_outs(name,
                             out if isinstance(out, tuple) else (out,))
                return out
            return monitored

        hook = {"wrapper": wrapper, "deliver": deliver_outs}
        self._op_hooks.append(hook)
        _op_registry.add_invoke_wrapper(wrapper)

        class _OpHookHandle:
            def detach(handle):
                _op_registry.remove_invoke_wrapper(wrapper)
                if hook in owner._op_hooks:
                    owner._op_hooks.remove(hook)

            def __enter__(handle):
                return handle

            def __exit__(handle, *exc):
                handle.detach()

        return _OpHookHandle()

    # ---------------- execution ----------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        if self.__dict__.get("_op_hooks"):
            # under autograd.record the kernel runs inside a vjp trace,
            # so concrete outputs are only visible at the tape's
            # post-vjp check — chain delivery there for the duration
            self._op_hook_active = True

            def tape_check(name, outs, _hooks=self._op_hooks):
                for h in _hooks:
                    h["deliver"](name, outs)
                if old_check is not None:
                    old_check(name, outs)

            old_check = _tape.set_output_check(tape_check)
            try:
                out = self.forward(*args, **kwargs)
            finally:
                self._op_hook_active = False
                _tape.set_output_check(old_check)
        else:
            out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def hybridize(self, active: bool = True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def _iter_blocks(self):
        yield self
        for c in self._children.values():
            yield from c._iter_blocks()

    def summary(self, *inputs):
        lines = [f"{type(self).__name__}:"]
        for k, p in self.collect_params().items():
            lines.append(f"  {k}: {p.shape}")
        s = "\n".join(lines)
        print(s)
        return s

    def __repr__(self):
        mods = "\n".join(f"  ({k}): {type(v).__name__}"
                         for k, v in self._children.items())
        return f"{type(self).__name__}(\n{mods}\n)" if mods else \
            f"{type(self).__name__}()"


class HybridBlock(Block):
    """Block that can fuse its forward into one compiled XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_fn = None
        self._trace_signatures: set = set()
        self._cached_params: List[Parameter] = []
        self._cached_out_info = {}
        self._state_idx: List[int] = []
        self._flags = {}
        self._backend = None
        self._partition_if_dynamic = True
        self._last_input_avals = None
        self._bucket_axis = None
        self._bucket_sizes = None
        self._jit_lru = OrderedDict()
        self._traced_fn = None
        self._bucket_shape_cache: Dict[Any, Any] = {}

    def hybridize(self, active: bool = True, backend=None, clear=True,
                  static_alloc: bool = False, static_shape: bool = False,
                  partition_if_dynamic: bool = True, bucket_axis=None,
                  bucket_sizes=None, **kwargs):
        """Reference block.py:1216. static_alloc/static_shape are accepted
        for parity; XLA's buffer assignment subsumes them.

        Retrace policy (reference dynamic CachedOp, cached_op.cc:696, and
        SURVEY §7 "dynamic shapes" hard part): ``bucket_axis`` opts into
        pad-to-bucket dispatch — traced inputs are zero-padded along that
        axis up to the next bucket size (``bucket_sizes`` ascending list, or
        next power of two when None) so variable-length workloads compile
        once per bucket instead of once per length; outputs are sliced back.
        Only valid when rows along the axis are independent (the contract of
        the reference's BucketingModule — masking stays the model's job; do
        not use with cross-row ops like BatchNorm over that axis).
        ``MXNET_CACHEDOP_BUCKET_AXIS`` sets a process default.
        ``MXNET_CACHEDOP_CACHE_SIZE`` (default 0 = unbounded) caps the
        number of live compiled signatures per block, LRU-evicted.
        """
        import os
        self._active = active
        if backend is None:
            backend = os.environ.get("MXNET_SUBGRAPH_BACKEND") or None
        self._backend = backend
        if bucket_axis is None:
            env_ax = os.environ.get("MXNET_CACHEDOP_BUCKET_AXIS", "")
            bucket_axis = int(env_ax) if env_ax else None
        self._bucket_axis = bucket_axis
        self._bucket_sizes = sorted(bucket_sizes) if bucket_sizes else None
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        if clear:
            self._cached_fn = None
            self._cached_out_info = {}
            self._jit_lru.clear()
            self._traced_fn = None
            self._bucket_shape_cache = {}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def optimize_for(self, x, *args, backend=None, clear=True, **kwargs):
        """Reference block.py:1141 — partition for a backend then build the
        cache. Backends hook in via parallel/partition.py."""
        self._backend = backend
        self.hybridize(True, backend=backend, clear=clear, **kwargs)
        return self(x, *args)

    # -------- cache construction --------
    def _ensure_shapes(self, args, kwargs=None):
        """Trigger deferred param init by one throwaway eager forward
        (the reference's deferred-compute trace performs shape inference;
        our layers infer shapes inline in forward).

        Hybridization is deactivated for the throwaway pass: child cached
        ops draw a per-call RNG key, which would advance the seeded global
        chain between deferred inits and break the "same seed ⇒ same
        weights" invariant between eager and hybrid execution (reference
        guarantees init values are independent of hybridize())."""
        incomplete = any(p._data is None
                         for p in self.collect_params().values())
        if not incomplete:
            return
        hybrids = [b for b in self._iter_blocks()
                   if isinstance(b, HybridBlock) and b._active]
        for b in hybrids:
            b._active = False
        try:
            with autograd.pause():
                self.forward(*args, **(kwargs or {}))
        finally:
            for b in hybrids:
                b._active = True

    def _build_cache(self, args, kwargs=None):
        self._ensure_shapes(args, kwargs)
        self._cached_out_info = {}
        params = [p for p in self.collect_params().values()
                  if p._data is not None]
        self._cached_params = params
        block = self
        info = self._cached_out_info

        def fn(rng_key, traced_leaves, arg_treedef, train_mode, static_spec,
               nd_mask, *param_datas):
            # (args, kwargs) were flattened with NDArray as LEAF so the
            # caller could keep the original handles (and their tape entries)
            # as the recorded op's inputs. static_spec holds non-array leaves
            # (python flags etc.) verbatim with _TRACED sentinels at traced
            # positions; nd_mask marks which traced leaves were NDArrays.
            it = iter(NDArray(l) if m else l
                      for l, m in zip(traced_leaves, nd_mask))
            leaves = [next(it) if s is _TRACED else s for s in static_spec]
            args_nd, kwargs_nd = jax.tree_util.tree_unflatten(
                arg_treedef, leaves)
            binding = ParamBinding(params, param_datas)
            push_trace_key(rng_key)
            prev = _tape.set_recording(False)
            prev_s = _tape.set_taping_suspended(True)
            prev_t = _tape.set_training(train_mode)
            try:
                with binding:
                    out = block.forward(*args_nd, **kwargs_nd)
            finally:
                _tape.set_recording(prev)
                _tape.set_taping_suspended(prev_s)
                _tape.set_training(prev_t)
                pop_trace_key()
            # functional state updates (BN running stats etc.)
            state_idx = binding.state_idx
            state_leaves = [binding.state[i] for i in state_idx]
            # flatten outputs with NDArray as LEAF (not pytree node) so the
            # call path can rebuild the structure around the tape-carrying
            # output handles
            out_leaves, out_treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda t: isinstance(t, NDArray))
            # keyed by the full static-arg signature: jax.jit retraces per
            # (treedef, train, static_spec, nd_mask), so output metadata must
            # too — a train-only key would go stale if the structure changes
            info[(train_mode, arg_treedef, static_spec, nd_mask)] = dict(
                out_treedef=out_treedef, n_out=len(out_leaves),
                state_idx=state_idx)
            return tuple(o._data if isinstance(o, NDArray) else o
                         for o in out_leaves) + tuple(state_leaves)

        if self._backend is not None:
            # reference BuildSubgraph/SubgraphProperty analog: transform the
            # traced callable before XLA compiles it (subgraph.py)
            from .. import subgraph as _subgraph
            fn = _subgraph.get_backend(self._backend).transform(
                fn, static_argnums=(2, 3, 4, 5))
        self._traced_fn = fn
        self._cached_fn = jax.jit(fn, static_argnums=(2, 3, 4, 5))

    # -------- retrace policy --------
    @staticmethod
    def _cache_cap() -> int:
        import os
        try:
            return int(os.environ.get("MXNET_CACHEDOP_CACHE_SIZE", "0"))
        except ValueError:
            return 0

    def _jit_for(self, shape_key):
        """LRU of jit wrappers keyed by input shapes/dtypes. Evicting a
        wrapper frees its compiled executable — the bound analog of the
        reference's per-bucket CachedOp binds."""
        cap = self._cache_cap()
        if cap <= 0:
            return self._cached_fn
        ent = self._jit_lru.get(shape_key)
        if ent is None:
            ent = jax.jit(self._traced_fn, static_argnums=(2, 3, 4, 5))
            self._jit_lru[shape_key] = ent
            while len(self._jit_lru) > cap:
                self._jit_lru.popitem(last=False)
        else:
            self._jit_lru.move_to_end(shape_key)
        return ent

    def _bucket_of(self, n: int) -> int:
        if self._bucket_sizes:
            for b in self._bucket_sizes:
                if b >= n:
                    return b
            return n  # beyond the ladder: compile per exact length
        b = 1
        while b < n:
            b <<= 1
        return b

    def _bucket_pad(self, traced):
        """Zero-pad traced leaves along self._bucket_axis to the bucket size
        (tape-recorded, so gradients flow back through the pad)."""
        ax = self._bucket_axis
        lengths = {int(l._data.shape[ax]) if isinstance(l, NDArray)
                   else int(l.shape[ax])
                   for l in traced
                   if getattr(l, "ndim", 0) > ax}
        if len(lengths) != 1:
            raise MXNetError(
                f"bucket_axis={ax} requires all traced inputs to share one "
                f"length along that axis, got {sorted(lengths)}")
        (orig,) = lengths
        tgt = self._bucket_of(orig)
        if tgt == orig:
            return traced, (ax, orig, tgt)
        padded = []
        for l in traced:
            if getattr(l, "ndim", 0) > ax:
                widths = [(0, 0)] * l.ndim
                widths[ax] = (0, tgt - orig)

                def _pad(d, _w=tuple(widths)):
                    import jax.numpy as jnp
                    return jnp.pad(d, _w)
                if isinstance(l, NDArray):
                    l = invoke_raw("bucket_pad", _pad, [l])
                else:
                    l = _pad(l)
            padded.append(l)
        return padded, (ax, orig, tgt)

    def _bucket_true_shapes(self, sig, orig_traced, rng_key, arg_treedef,
                            train, static_spec, nd_mask):
        """Abstract-trace (jax.eval_shape — no compile) the forward at the
        ORIGINAL length to learn each output's true shape. Exact unpad rule:
        slice any output axis whose padded dim differs from the true dim —
        an output that coincidentally has bucket-size many classes is left
        alone, and padding that lands on a transposed axis is still cut."""
        key = (sig, tuple(
            tuple((l._data if isinstance(l, NDArray) else l).shape)
            for l in orig_traced))
        if key in self._bucket_shape_cache:
            return self._bucket_shape_cache[key]
        sds = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731

        def part(k, leaves, pd):
            return self._traced_fn(k, leaves, arg_treedef, train,
                                   static_spec, nd_mask, *pd)
        try:
            out = jax.eval_shape(
                part, sds(rng_key),
                tuple(sds(l._data if isinstance(l, NDArray) else l)
                      for l in orig_traced),
                [sds(p._data._data) for p in self._cached_params])
            shapes = tuple(tuple(o.shape) for o in out)
        except Exception:
            shapes = None  # fall back to the axis-dim heuristic
        self._bucket_shape_cache[key] = shapes
        return shapes

    def _bucket_unpad(self, outs, restore, true_shapes=None):
        ax, orig, tgt = restore
        if tgt == orig:
            return outs
        sliced = []
        for i, o in enumerate(outs):
            d = o._data if isinstance(o, NDArray) else o
            if true_shapes is not None and i < len(true_shapes):
                ts = true_shapes[i]
                if tuple(d.shape) != ts:
                    def _slc(x, _ts=ts):
                        return x[tuple(slice(0, s) for s in _ts)]
                    o = invoke_raw(
                        "bucket_slice", _slc,
                        [o if isinstance(o, NDArray) else NDArray(o)])
            elif getattr(d, "ndim", 0) > ax and d.shape[ax] == tgt:
                def _slc(x, _ax=ax, _n=orig):
                    return jax.lax.slice_in_dim(x, 0, _n, axis=_ax)
                o = invoke_raw("bucket_slice", _slc,
                               [o if isinstance(o, NDArray) else NDArray(o)])
            sliced.append(o)
        return sliced

    def _call_cached_op(self, *args, **kwargs):
        """Reference block.py:1095 → CachedOp::Forward. One tape node per
        call; backward differentiates the whole compiled computation."""
        if self._cached_fn is None:
            self._build_cache(args, kwargs)
        params = self._cached_params
        # NDArray stays a LEAF here: the original handles carry the tape
        # entries that link this cached op to upstream recorded ops (a raw
        # pytree flatten would strip them and sever the autograd chain).
        # Array leaves become traced inputs; anything else (python flags,
        # strings, None) is static and baked into the jit signature.
        all_leaves, arg_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda t: isinstance(t, NDArray))
        traced = [l for l in all_leaves
                  if isinstance(l, (NDArray, onp.ndarray, jax.Array))]
        static_spec = tuple(
            _TRACED if isinstance(l, (NDArray, onp.ndarray, jax.Array))
            else l for l in all_leaves)
        restore = None
        orig_traced = traced
        if self._bucket_axis is not None and traced:
            traced, restore = self._bucket_pad(traced)
        nd_mask = tuple(isinstance(l, NDArray) for l in traced)
        rng_key = next_key()
        train = _tape.is_training()

        shape_key = (train, arg_treedef, static_spec, nd_mask, tuple(
            (tuple((l._data if isinstance(l, NDArray) else l).shape),
             str((l._data if isinstance(l, NDArray) else l).dtype))
            for l in traced))
        # dispatch-signature record: one entry per DISTINCT compiled
        # signature (post-bucketing) — how tests observe the retrace
        # policy without poking jit's evictable internal cache
        self._trace_signatures.add(shape_key)
        fn = self._jit_for(shape_key)

        def op_fn(*leaves_and_params, _fn=fn, _treedef=arg_treedef,
                  _key=rng_key, _n_args=len(traced), _train=train,
                  _static=static_spec, _mask=nd_mask):
            a = leaves_and_params[:_n_args]
            pd = leaves_and_params[_n_args:]
            return _fn(_key, a, _treedef, _train, _static, _mask, *pd)

        inputs = ([l if isinstance(l, NDArray) else NDArray(l)
                   for l in traced] +
                  [p._data for p in params])
        # first call per static signature: lower once (traces fn → info)
        sig = (train, arg_treedef, static_spec, nd_mask)
        if sig not in self._cached_out_info:
            fn.lower(rng_key,
                     tuple(l._data for l in inputs[:len(traced)]),
                     arg_treedef, train, static_spec, nd_mask,
                     *[p._data._data for p in params])
        info = self._cached_out_info[sig]
        n_total = info["n_out"] + len(info["state_idx"])
        result = invoke_raw(f"cached_op_{self._name}", op_fn, inputs,
                            n_outputs=n_total)
        result = result if isinstance(result, tuple) else (result,)
        outs = result[:info["n_out"]]
        states = result[info["n_out"]:]
        if restore is not None and restore[1] != restore[2]:
            true_shapes = self._bucket_true_shapes(
                sig, orig_traced, rng_key, arg_treedef, train, static_spec,
                nd_mask)
            outs = tuple(self._bucket_unpad(list(outs), restore,
                                            true_shapes))
        with autograd.pause():
            for i, s in zip(info["state_idx"], states):
                # REBIND (not mutate) so an enclosing hybridized parent's
                # trace detects this as a state update too (id check in its
                # _build_cache); in-place mutation would be invisible to it.
                # DETACH from the tape: stats updates are non-differentiable
                # (reference BN aux states bypass autograd), and a retained
                # entry would chain the next iteration's graph into this
                # (freed) one via the moving-stats input.
                s._tape_entry = None
                params[i]._data = s
        # rebuild output structure around the tape-carrying handles
        return jax.tree_util.tree_unflatten(info["out_treedef"], list(outs))

    def __call__(self, *args, **kwargs):
        all_inputs = args + tuple(kwargs.values())
        if not _in_trace(all_inputs):
            # remember input signature for export (trace_block_to_symbol)
            self._last_input_avals = [
                jax.ShapeDtypeStruct(a._data.shape, a._data.dtype)
                for a in all_inputs if isinstance(a, NDArray)]
        if self._active and not _in_trace(all_inputs):
            for hook in self._forward_pre_hooks:
                hook(self, args)
            out = self._call_cached_op(*args, **kwargs)
            for hook in self._forward_hooks:
                hook(self, args, out)
            return out
        # inside an enclosing hybridized parent's trace, run the raw forward
        # so the whole model compiles into ONE flat XLA computation
        return super().__call__(*args, **kwargs)

    # -------- export (reference block.py:1296) --------
    def export(self, path: str, epoch: int = 0, remove_amp_cast=True):
        """Save architecture descriptor + params; re-importable by
        SymbolBlock.imports (format: symbol.py JSON graph)."""
        from ..symbol.symbol import trace_block_to_symbol
        params = self.collect_params()
        sym = trace_block_to_symbol(self)
        sym_file = f"{path}-symbol.json"
        param_file = f"{path}-{epoch:04d}.params"
        sym.save(sym_file)
        nd_utils.save(param_file,
                      {k: v.data() for k, v in params.items()})
        return sym_file, param_file

    def infer_shape(self, *args):
        self._ensure_shapes(args)

    def infer_type(self, *args):
        """Infer Parameter dtypes from the inputs (reference
        block.py:1292): floating-point params follow the widest
        floating input dtype; integer params are untouched."""
        import jax.numpy as jnp
        in_dtypes = [a._data.dtype for a in args
                     if isinstance(a, NDArray) and
                     jnp.issubdtype(a._data.dtype, jnp.floating)]
        if not in_dtypes:
            return
        target = in_dtypes[0]
        for d in in_dtypes[1:]:
            target = jnp.promote_types(target, d)
        for p in self.collect_params().values():
            if p._data is None:
                p.dtype = target  # dtype for the deferred allocation
            elif jnp.issubdtype(p._data._data.dtype, jnp.floating):
                p.cast(target)
            # initialized non-floating params keep their dtype

    def hybrid_forward(self, F, x, *args, **kwargs):
        """1.x-style override point (reference block.py:1448): when a
        subclass defines it, the default ``forward`` calls it with
        ``F = mx.nd`` and the block's materialized Parameters as
        keyword arguments."""
        raise NotImplementedError

    def forward(self, *args, **kwargs):
        if type(self).hybrid_forward is not HybridBlock.hybrid_forward:
            from .. import ndarray as F
            pdata = {}
            for name, p in self._reg_params.items():
                if p._data is None:
                    raise MXNetError(
                        f"hybrid_forward compat path: parameter {name} "
                        "is uninitialized; construct the layer with "
                        "known input sizes (deferred shape inference "
                        "needs a 2.0-style forward)")
                pdata[name] = p.data()
            return self.hybrid_forward(F, *args, **kwargs, **pdata)
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Run a saved symbolic graph as a block (reference block.py:1479).
    Fleshed out with the symbol module; imports() loads an exported pair."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__()
        self._symbol_outputs = outputs
        self._symbol_inputs = inputs
        self._symbol_params = params or {}
        for k, v in self._symbol_params.items():
            p = Parameter(name=k, shape=v.shape)
            p.set_data(v)
            self._reg_params[k.replace(".", "_")] = p

    @staticmethod
    def imports(symbol_file: str, input_names, param_file: Optional[str] = None,
                ctx=None):
        from ..symbol.symbol import Symbol
        sym = Symbol.load(symbol_file)
        params = nd_utils.load(param_file) if param_file else {}
        if isinstance(input_names, str):
            input_names = [input_names]
        blk = SymbolBlock(sym, input_names, params)
        return blk

    def forward(self, *args):
        from ..symbol import executor as sym_executor
        sym = self._symbol_outputs
        arg_names = sym.list_arguments()
        feeds = {}
        # positional inputs map to the symbol's non-param arguments in order
        input_slots = [n for n in arg_names if n not in self._symbol_params]
        for n, a in zip(input_slots, args):
            feeds[n] = a if isinstance(a, NDArray) else NDArray(a)
        for k, v in self._symbol_params.items():
            feeds[k] = v
        return sym_executor.eval_symbol(sym, feeds)
