"""Gluon utilities (reference: python/mxnet/gluon/utils.py).

``split_and_load`` keeps its API but gains a TPU-native mode: with
``even_split`` over a device list it returns per-device slices like the
reference; with a mesh axis (parallel module) the idiomatic path is a single
batch-sharded array instead.
"""
from __future__ import annotations

import math
from typing import List

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray


def split_data(data, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, lo, hi))
    return slices


def split_and_load(data, ctx_list, batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Split batch along batch_axis and load each slice onto one ctx
    (reference gluon.utils.split_and_load)."""
    if not isinstance(data, NDArray):
        data = NDArray(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm: float, check_isfinite: bool = True):
    """Rescale arrays so the joint L2 norm <= max_norm (reference
    gluon.utils.clip_global_norm)."""
    total = 0.0
    norms = []
    for a in arrays:
        n2 = float((a * a).sum().asnumpy())
        norms.append(n2)
        total += n2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        raise MXNetError(f"global norm is not finite: {total}")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


_APACHE_REPO_URL = "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"


def _get_repo_url():
    """Base URL for model-zoo/dataset artifacts, overridable with
    MXNET_GLUON_REPO (reference gluon/utils.py _get_repo_url). Zero-egress
    builds point it at a local mirror directory via file://."""
    import os
    url = os.environ.get("MXNET_GLUON_REPO", _APACHE_REPO_URL)
    if not url.endswith("/"):
        url += "/"
    return url


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference gluon.utils.download. This build runs zero-egress; only
    file:// and existing local paths are served."""
    import os
    import shutil
    if url.startswith("file://"):
        src = url[7:]
        dst = path or os.path.basename(src)
        if os.path.isdir(dst):
            dst = os.path.join(dst, os.path.basename(src))
        if not os.path.exists(dst) or overwrite:
            shutil.copyfile(src, dst)
        return dst
    if os.path.exists(url):
        return url
    raise MXNetError(
        "network downloads unavailable (zero-egress environment); "
        f"cannot fetch {url}")
