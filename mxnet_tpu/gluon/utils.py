"""Gluon utilities (reference: python/mxnet/gluon/utils.py).

``split_and_load`` keeps its API but gains a TPU-native mode: with
``even_split`` over a device list it returns per-device slices like the
reference; with a mesh axis (parallel module) the idiomatic path is a single
batch-sharded array instead.
"""
from __future__ import annotations

import math
from typing import List

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray


def split_data(data, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, lo, hi))
    return slices


def split_and_load(data, ctx_list, batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Split batch along batch_axis and load each slice onto one ctx
    (reference gluon.utils.split_and_load)."""
    if not isinstance(data, NDArray):
        data = NDArray(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm: float, check_isfinite: bool = True):
    """Rescale arrays so the joint L2 norm <= max_norm (reference
    gluon.utils.clip_global_norm)."""
    total = 0.0
    norms = []
    for a in arrays:
        n2 = float((a * a).sum().asnumpy())
        norms.append(n2)
        total += n2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        raise MXNetError(f"global norm is not finite: {total}")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


_APACHE_REPO_URL = "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"


def _get_repo_url():
    """Base URL for model-zoo/dataset artifacts, overridable with
    MXNET_GLUON_REPO (reference gluon/utils.py _get_repo_url). Zero-egress
    builds point it at a local mirror directory via file://."""
    import os
    url = os.environ.get("MXNET_GLUON_REPO", _APACHE_REPO_URL)
    if not url.endswith("/"):
        url += "/"
    return url


def check_sha1(filename, sha1_hash) -> bool:
    """True when the file's sha1 matches (reference gluon.utils.check_sha1;
    prefix matches are accepted like the reference's short hashes)."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha1.update(chunk)
    digest = sha1.hexdigest()
    return digest == sha1_hash or digest.startswith(sha1_hash)


def _fetch_once(url, tmp_path):
    """One transfer attempt into ``tmp_path``. file:// and existing local
    paths are served directly (zero-egress builds); http(s) goes through
    urllib and surfaces transient failures as exceptions for the retry
    loop."""
    import os
    import shutil
    if url.startswith("file://"):
        shutil.copyfile(url[7:], tmp_path)
        return
    if os.path.exists(url):
        shutil.copyfile(url, tmp_path)
        return
    import urllib.request
    with urllib.request.urlopen(url, timeout=30) as r, \
            open(tmp_path, "wb") as f:
        shutil.copyfileobj(r, f)


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference gluon.utils.download, hardened: transient failures are
    retried with exponential backoff + jitter, the payload is staged to
    a temp file and sha1-verified BEFORE an atomic ``os.replace`` into
    place (a corrupt or torn transfer never lands at the destination,
    and the corrupt temp is deleted), and an existing destination that
    already matches ``sha1_hash`` short-circuits."""
    import os
    import random
    import time
    dst = path or url.split("/")[-1]
    if os.path.isdir(dst):
        dst = os.path.join(dst, url.split("/")[-1])
    if os.path.exists(dst) and not overwrite and \
            (sha1_hash is None or check_sha1(dst, sha1_hash)):
        return dst
    retries = max(1, int(retries))
    tmp = f"{dst}.tmp-{os.getpid()}"
    last_err = None
    for attempt in range(retries):
        try:
            _fetch_once(url, tmp)
            if sha1_hash and not check_sha1(tmp, sha1_hash):
                raise MXNetError(
                    f"downloaded file {url} failed sha1 verification "
                    f"(expected {sha1_hash})")
            os.replace(tmp, dst)
            return dst
        except Exception as e:
            try:
                os.unlink(tmp)   # never leave a corrupt partial behind
            except OSError:
                pass
            last_err = e
            if attempt + 1 < retries:
                delay = min(10.0, 0.5 * (2 ** attempt)) \
                    * (1.0 + 0.5 * random.random())
                time.sleep(delay)
    raise MXNetError(
        f"cannot fetch {url} after {retries} attempts "
        f"({type(last_err).__name__}: {last_err}); note this build runs "
        "zero-egress — point MXNET_GLUON_REPO at a file:// mirror") \
        from last_err
