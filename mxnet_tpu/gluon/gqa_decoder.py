"""GQA transformer decode stack: the second serving decode workload.

A minimal multi-layer decoder-only transformer implementing the
:class:`~mxnet_tpu.serving.decode.DecodeEngine` model protocol
(``decode_step`` / ``prefill_chunk`` / ``verify_chunk``) over the SAME
paged KV layout as the reference RNN — per-layer K/V pages of shape
``(num_layers, num_pages, page_size, num_kv_heads, head_dim)`` read and
written through the engine's page table.

Grouped-query attention is the point: the model queries with
``num_heads`` heads but caches only ``num_kv_heads`` K/V heads
(``num_heads`` must be a multiple), so the paged cache is
``num_heads / num_kv_heads``× smaller per token than an MHA cache of
the same query width. The broadcast across query groups happens inside
:func:`~mxnet_tpu.ops.attention.paged_decode_attention` — the engine
only sees the smaller cache geometry via the model's ``num_kv_heads``
attribute.

Parity discipline (the property speculative decode leans on): all
three entry points process one token through the SAME single-token
block — ``decode_step`` directly, ``prefill_chunk`` and
``verify_chunk`` via a ``lax.scan`` over positions. A transformer has
no recurrent carry, so the engine's ``h``/``c`` state rows are dummy
``(slots, 1)`` zeros passed through untouched; K/V pages ARE the whole
decode state, which also makes prefix sharing exact for free.
"""
from __future__ import annotations

import numpy as onp

import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ops.attention import paged_decode_attention

__all__ = ["GQADecoder"]


def _rmsnorm(x, g, eps: float = 1e-6):
    return x * g * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1,
                                      keepdims=True) + eps)


class GQADecoder:
    """Decoder-only transformer with grouped-query attention over the
    engine's paged KV cache.

    Per layer: pre-norm -> q/k/v projections (q: ``num_heads`` heads,
    k/v: ``num_kv_heads`` heads) -> K/V page write at this token's
    position -> paged attention (GQA broadcast) -> output projection
    residual -> pre-norm MLP residual. Logits tie the embedding.
    """

    def __init__(self, vocab: int = 64, d_model: int = 32,
                 num_heads: int = 4, num_kv_heads: int = 2,
                 num_layers: int = 2, max_len: int = 512,
                 seed: int = 0):
        if d_model % num_heads:
            raise MXNetError(f"d_model={d_model} not divisible by "
                             f"num_heads={num_heads}")
        if num_heads % num_kv_heads:
            raise MXNetError(
                f"num_heads={num_heads} not a multiple of "
                f"num_kv_heads={num_kv_heads} (GQA groups must be even)")
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads)
        self.num_layers = int(num_layers)
        self.head_dim = self.d_model // self.num_heads
        self.max_len = int(max_len)
        rng = onp.random.RandomState(seed)
        H = self.d_model
        kvw = self.num_kv_heads * self.head_dim

        def mat(*shape, scale=0.3):
            return jnp.asarray(
                rng.normal(0.0, scale, shape).astype("float32"))

        self.params = {
            "embed": mat(self.vocab, H, scale=0.5),
            "pos": mat(self.max_len, H, scale=0.2),
            "lnf": jnp.ones((H,), "float32"),
            "layers": [
                {
                    "ln1": jnp.ones((H,), "float32"),
                    "wq": mat(H, H), "wk": mat(H, kvw),
                    "wv": mat(H, kvw), "wo": mat(H, H),
                    "ln2": jnp.ones((H,), "float32"),
                    "w1": mat(H, 2 * H), "w2": mat(2 * H, H),
                }
                for _ in range(self.num_layers)
            ],
        }

    def init_state(self, slots: int):
        # no recurrent carry: (slots, 1) dummies the engine threads
        # through every program unchanged
        return (jnp.zeros((slots, 1), "float32"),
                jnp.zeros((slots, 1), "float32"))

    # -- the single-token block every entry point shares (parity by
    #    construction across decode / prefill / verify)
    def _block(self, params, tokens, pos, k_pages, v_pages, pidx, poff,
               table, lengths):
        S = tokens.shape[0]
        Hq, Hkv, D = self.num_heads, self.num_kv_heads, self.head_dim
        p = jnp.clip(pos, 0, self.max_len - 1)
        x = params["embed"][tokens] + params["pos"][p]
        for li, lp in enumerate(params["layers"]):
            y = _rmsnorm(x, lp["ln1"])
            q = (y @ lp["wq"]).reshape(S, Hq, D)
            k = (y @ lp["wk"]).reshape(S, Hkv, D)
            v = (y @ lp["wv"]).reshape(S, Hkv, D)
            k_pages = k_pages.at[li, pidx, poff].set(
                k.astype(k_pages.dtype))
            v_pages = v_pages.at[li, pidx, poff].set(
                v.astype(v_pages.dtype))
            attn = paged_decode_attention(q, k_pages[li], v_pages[li],
                                          table, lengths)
            x = x + attn.reshape(S, -1) @ lp["wo"]
            y2 = _rmsnorm(x, lp["ln2"])
            x = x + jnp.maximum(y2 @ lp["w1"], 0.0) @ lp["w2"]
        logits = _rmsnorm(x, params["lnf"]) @ params["embed"].T
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return nxt, k_pages, v_pages

    def decode_step(self, params, tokens, h, c, k_pages, v_pages,
                    pidx, poff, table, lengths, active):
        """One iteration over every slot: write this token's K/V in
        every layer, attend over the slot's paged history, emit the
        greedy next token. Inactive slots write the null page and
        bit-preserve their token."""
        pidx = jnp.where(active, pidx, 0)
        poff = jnp.where(active, poff, 0)
        nxt, k_pages, v_pages = self._block(
            params, tokens, lengths - 1, k_pages, v_pages, pidx, poff,
            table, lengths)
        nxt = jnp.where(active, nxt, tokens)
        return nxt, h, c, k_pages, v_pages

    def prefill_chunk(self, params, tokens, h, c, k_pages, v_pages,
                      start_len, n_valid, reset, active, table,
                      page_size: int):
        """Consume up to ``tokens.shape[1]`` prompt tokens through the
        same single-token block, one position per scan step (each
        position's attention must see the chunk's earlier writes). The
        returned token is the greedy continuation of the last valid
        position."""
        S, C = tokens.shape

        def body(carry, t):
            kp, vp, last = carry
            tok = tokens[:, t]
            valid = active & (t < n_valid)
            pos = start_len + t
            page = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            pg = jnp.where(valid, page, 0)
            off = jnp.where(valid, pos % page_size, 0)
            lengths = jnp.where(valid, pos + 1, 1)
            nxt, kp, vp = self._block(params, tok, pos, kp, vp, pg,
                                      off, table, lengths)
            last = jnp.where(valid, nxt, last)
            return (kp, vp, last), None

        (k_pages, v_pages, last), _ = lax.scan(
            body, (k_pages, v_pages,
                   jnp.zeros((S,), jnp.int32)), jnp.arange(C))
        nxt = jnp.where(active, last, 0)
        return nxt, h, c, k_pages, v_pages

    def verify_chunk(self, params, tokens, h, c, k_pages, v_pages,
                     start_len, n_draft, active, table,
                     page_size: int):
        """Score the committed token + drafts in one dispatch: the scan
        body IS the decode block, so position t emits exactly what
        sequential greedy decode would. State trajectories are the
        dummy carries tiled per position (nothing to roll back — the
        pages hold all the state and acceptance is length
        bookkeeping)."""
        S, K = tokens.shape

        def body(kv, t):
            kp, vp = kv
            tok = tokens[:, t]
            valid = active & (t < n_draft)
            pos = start_len + t
            page = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            pg = jnp.where(valid, page, 0)
            off = jnp.where(valid, pos % page_size, 0)
            lengths = jnp.where(valid, pos + 1, 1)
            y, kp, vp = self._block(params, tok, pos, kp, vp, pg, off,
                                    table, lengths)
            return (kp, vp), y

        (k_pages, v_pages), ys = lax.scan(
            body, (k_pages, v_pages), jnp.arange(K))
        hs = jnp.broadcast_to(h[None], (K,) + h.shape)
        cs = jnp.broadcast_to(c[None], (K,) + c.shape)
        return ys.T, hs, cs, k_pages, v_pages
