"""Fused whole-train-step compilation (``Trainer.compile_step``).

The reference MXNet fuses the UPDATE side of training (multi-tensor
``multi_sgd_*`` kernels, ``update_on_kvstore``) but still pays an
imperative dispatch per op and a host boundary between backward and the
optimizer. Here the canonical Gluon loop

    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)

compiles into ONE donated-buffer XLA program per input-shape bucket:
forward (via the same functional binding the CachedOp uses —
``block.ParamBinding``), ``jax.value_and_grad`` of the summed loss over
the parameter pytree (the seed-ones equivalent of ``loss.backward()``),
gradient rescale/clip, the data-parallel reduction (a no-op/psum XLA
inserts for single-process stores; host ``pushpull_list`` between two
programs for dist stores), and the optimizer's ``_rule`` — the idiom the
fusion literature shows dominates TPU efficiency (arXiv:2301.13062) and
that enables in-graph weight-update optimization (arXiv:2004.13336).

Contracts:

- **Traced hyperparameters.** lr/wd/update-count/rescale_grad (and the
  clip bound) enter the program as traced arguments packed in small host
  arrays — ``trainer.learning_rate = x``, a scheduler tick, or a new
  ``step(batch_size)`` NEVER retrace. One compile per input-shape bucket
  (LRU-capped by ``MXNET_FUSED_STEP_CACHE_SIZE``, like the CachedOp's
  ``_jit_lru``).
- **Donation.** Weight and optimizer-state buffers are donated
  (``donate_argnums``) so XLA updates them in place in HBM; after each
  call the results are written back INTO the same ``Parameter._data``
  and state NDArray handles (``Parameter._write_fused``), so handles
  users hold from ``param.data()`` stay valid. Raw ``jax.Array`` objects
  captured from ``param.data()._data`` before a step are invalidated by
  donation — snapshot via ``asnumpy()``/``copy`` instead.
- **Transparent fallback.** Sparse-grad or multi-precision parameters,
  ``update_on_kvstore`` stores, and blocks whose forward cannot trace
  (host-side numpy, data-dependent Python control flow) fall back to the
  eager record/backward/step loop with identical numerics.
- **ZeRO-1 sharded update.** When a ``DeviceMesh`` with a data-parallel
  axis is active (``parallel.make_mesh``), the redundant replicated
  weight update is cross-replica sharded (arXiv:2004.13336): gradients
  are constrained to a flat 1/N-per-replica layout (XLA's weight-update
  sharding pass turns the gradient all-reduce into a reduce-scatter),
  the optimizer rule runs on each replica's shard, and the new weights
  all-gather back to replicated. Optimizer state (momenta, Adam moments,
  fp32 master copies of multi-precision params) lives permanently
  sharded via ``NamedSharding`` — per-replica state memory drops ~N×.
  Parameters smaller than ``MXNET_ZERO_SHARD_MIN_SIZE`` elements bucket
  into one fused shard per dtype so tiny tensors don't pay a collective
  each. See ``_ZeroShardPlan``.
- **Numerics instrumentation.** ``numerics='global'|'per_layer'``
  (``MXNET_NUMERICS``) threads auxiliary on-device statistics through
  the same program — global grad/param norms, update/weight ratio,
  per-dtype non-finite counts, per-layer norms — as pure reductions of
  values the step already computes: params/loss stay BIT-EXACT vs
  numerics=off, and under ZeRO the reductions are psum-composed from
  the flat shards so every replica reports true global norms
  (telemetry/numerics.py; docs/OBSERVABILITY.md "numerics").
"""
from __future__ import annotations

import logging
import os
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from .. import _tape
from ..analysis import guard as _tguard
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray.random import next_key, push_trace_key, pop_trace_key
from ..testing.faults import fault_point
from .block import ParamBinding, _TRACED

__all__ = ["CompiledTrainStep", "TrainLoop"]

_LOG = logging.getLogger("mxnet_tpu.fused_step")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# elastic device-loss detection (elastic/detect.py), lazily cached the
# same way — classifies failures escaping the step-dispatch seam
_EDET = None


def _edetect():
    global _EDET
    if _EDET is None:
        from ..elastic import detect as _d
        _EDET = _d
    return _EDET

_ARRAY_TYPES = (NDArray, onp.ndarray, jax.Array)


def _place_on_mesh(mesh, axis: str, d):
    """Mesh input layout (batch-shard dim0 when divisible, else
    replicate) — shared with the device prefetcher via
    ``parallel.mesh.place_on_mesh``."""
    from ..parallel.mesh import place_on_mesh
    return place_on_mesh(mesh, axis, d)


def _zero_min_size() -> int:
    """ZeRO bucket floor (elements): autotune override >
    ``MXNET_ZERO_SHARD_MIN_SIZE`` > 2048 (the ``zero.shard_min_size``
    tunable — tuning/space.py)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("zero.shard_min_size")
    if not found:
        v = os.environ.get("MXNET_ZERO_SHARD_MIN_SIZE", "2048")
    try:
        return int(v)
    except (TypeError, ValueError):
        return 2048


def _zero_bucket_bytes() -> int:
    """ZeRO gradient communication bucket size (bytes): autotune
    override > ``MXNET_ZERO_BUCKET_BYTES`` > 4 MiB.  ``<= 0`` selects
    the monolithic serial baseline (one collective payload over every
    unit: backward -> reduce-scatter -> update -> all-gather with no
    independent compute left to hide the wire time)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("zero.bucket_bytes")
    if not found:
        v = os.environ.get("MXNET_ZERO_BUCKET_BYTES", str(4 << 20))
    try:
        return int(v)
    except (TypeError, ValueError):
        return 4 << 20


def zero_bucket_schedule(units, bucket_bytes: int):
    """Partition ZeRO unit indices into size-bounded communication
    buckets, in REVERSE unit order — backward produces the LAST
    layer's gradients first, so the first bucket's reduce-scatter can
    launch while earlier layers' backward compute still runs
    (reverse-topological grad availability, arXiv:1909.09756's
    compute/comm overlap checklist).  A bucket's units concatenate into
    ONE flat collective payload (parallel/collectives.py
    ``reduce_scatter_bucketed``), so buckets never mix update dtypes.
    ``bucket_bytes <= 0`` returns the fewest possible buckets (one per
    contiguous update-dtype run, usually one total): the monolithic
    serial baseline."""

    def _ub(u):
        try:
            return int(u["padded"]) * onp.dtype(u["upd_dtype"]).itemsize
        except Exception:    # pragma: no cover - defensive
            return int(u["padded"]) * 4

    serial = bucket_bytes is None or int(bucket_bytes) <= 0
    bucket_bytes = None if serial else int(bucket_bytes)
    order = range(len(units)) if serial else reversed(range(len(units)))
    buckets, cur, cur_b, cur_dt = [], [], 0, None
    for k in order:
        u = units[k]
        ub = _ub(u)
        # forward dtype AND update dtype must both be uniform within a
        # bucket: the packed forward buffer is in forward dtype, the
        # collective payload in update dtype
        dt = (str(u["upd_dtype"]), str(u["dtypes"][0]))
        if cur and (dt != cur_dt or
                    (not serial and cur_b + ub > bucket_bytes)):
            buckets.append(cur)
            cur, cur_b = [], 0
        cur.append(k)
        cur_b += ub
        cur_dt = dt
    if cur:
        buckets.append(cur)
    return buckets


def _register_tunables():
    """The ZeRO bucket-floor tunable, declared next to the constant it
    makes sweepable: the floor trades collective COUNT (every solo
    param is one reduce-scatter + one all-gather) against update-fusion
    granularity. Any packing is numerically identical — the update is
    elementwise over the flat shards — so the knob is pure speed."""
    from ..tuning.space import Tunable, register
    register(Tunable(
        "zero.shard_min_size", default=2048,
        grid=(512, 2048, 8192, 32768),
        env="MXNET_ZERO_SHARD_MIN_SIZE", parse=int,
        valid=lambda v, _c: int(v) >= 1,
        seam="gluon.fused_step._zero_min_size() -> _ZeroShardPlan "
             "solo-vs-bucketed unit split",
        scope="train", affects_program=True,
        doc="element floor for a param to get its own RS/AG pair "
            "under the ZeRO-1 sharded update"))
    register(Tunable(
        "zero.bucket_bytes", default=4 << 20,
        grid=(0, 1 << 20, 4 << 20, 16 << 20),
        env="MXNET_ZERO_BUCKET_BYTES", parse=int,
        valid=lambda v, _c: int(v) >= 0,
        seam="gluon.fused_step._zero_bucket_bytes() -> "
             "zero_bucket_schedule comm bucketing (0 = monolithic "
             "serial baseline)",
        scope="train", affects_program=True,
        doc="byte bound per ZeRO gradient communication bucket — "
            "smaller buckets expose more collectives to latency "
            "hiding, larger ones amortize per-collective latency; "
            "the analytical autotuner scores both against modeled "
            "exposed comm seconds (analysis/overlap.py)"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break steps
    _LOG.debug("fused_step tunable registration failed", exc_info=True)


def _analysis_mode(requested: Optional[str]) -> Optional[str]:
    """Normalize the ``analyze=`` kwarg / MXNET_ANALYSIS env setting to
    one of None | 'report' | 'warn' | 'raise'."""
    v = requested if requested is not None \
        else os.environ.get("MXNET_ANALYSIS")
    if v is None or v is False:
        return None
    if v is True:
        return "warn"
    v = str(v).strip().lower()
    if v in ("", "0", "off", "false", "no", "none"):
        return None
    if v in ("1", "report"):
        return "report"
    if v in ("warn", "log"):
        return "warn"
    if v in ("raise", "error", "strict"):
        return "raise"
    _LOG.warning("unknown analysis mode %r (MXNET_ANALYSIS); "
                 "treating as 'warn'", v)
    return "warn"


class _ZeroShardPlan:
    """Host-side layout of the ZeRO-1 sharded weight update
    (arXiv:2004.13336 "Automatic Cross-Replica Sharding of Weight Update
    in Data-Parallel Training").

    Trainable parameters map to UNITS:

    - every parameter with flat size >= ``MXNET_ZERO_SHARD_MIN_SIZE``
      (and every multi-precision parameter) is its own unit;
    - smaller parameters concatenate into one bucket unit per dtype, so
      tiny tensors share a single reduce-scatter/all-gather instead of
      paying one collective each (their hyperparameters pack into
      per-element vectors — ``Optimizer.pack_shard_hparams``).

    Each unit is a flat buffer zero-padded to a multiple of the dp-axis
    size; its optimizer state (and the fp32 master copy of a
    multi-precision unit) lives as ``NamedSharding``-partitioned arrays,
    1/N per replica. Weights stay replicated for the forward; inside the
    compiled step the flat gradient is constrained to the sharded layout
    (XLA's weight-update-sharding pass converts the gradient all-reduce
    into a reduce-scatter feeding it), the elementwise optimizer rule
    runs shard-locally, and the new weights are constrained back to
    replicated (an all-gather).
    """

    def __init__(self, trainer, mesh, axis: str):
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.mesh import zero_shard_pad
        self.mesh = mesh
        self.axis = axis
        self.n_shards = int(mesh.shape[axis])
        self.shard = NamedSharding(mesh.mesh, PartitionSpec(axis))
        self.repl = NamedSharding(mesh.mesh, PartitionSpec())
        opt = trainer._optimizer
        params = trainer._params
        min_size = _zero_min_size()

        raw_units = []
        small: "dict[str, list]" = {}
        for j, p in enumerate(params):
            d = p._data._data
            mp = opt.multi_precision and d.dtype in (jnp.float16,
                                                     jnp.bfloat16)
            if mp or int(d.size) >= min_size:
                raw_units.append((tuple([j]), mp))
            else:
                small.setdefault(str(d.dtype), []).append(j)
        for js in small.values():
            raw_units.append((tuple(js), False))

        self.units = []
        self.states = []       # per unit: tuple of flat sharded NDArrays
        self.masters = []      # flat sharded fp32 masters (mp units only)
        self.master_slot = {}  # unit index -> slot in self.masters
        for members, mp in raw_units:
            shapes = tuple(tuple(params[j]._data._data.shape)
                           for j in members)
            dtypes = tuple(params[j]._data._data.dtype for j in members)
            sizes = tuple(int(onp.prod(s)) if s else 1 for s in shapes)
            total = int(sum(sizes))
            self.units.append(dict(
                members=members, shapes=shapes, dtypes=dtypes, sizes=sizes,
                total=total, padded=zero_shard_pad(total, self.n_shards),
                mp=mp, upd_dtype=jnp.float32 if mp else dtypes[0]))
        restored = getattr(trainer, "_restored_masters", {})
        for k, unit in enumerate(self.units):
            if unit["mp"]:
                j = unit["members"][0]
                if j in restored:
                    # checkpoint resume: the saved fp32 master carries
                    # low-order bits the fp16 weight lost — recasting
                    # would break bit-exact resume (checkpoint/state.py)
                    master = jnp.asarray(restored.pop(j), jnp.float32)
                else:
                    master = params[j]._data._data.astype(jnp.float32)
                self.master_slot[k] = len(self.masters)
                self.masters.append(NDArray(self._flat_shard(
                    master.reshape(-1), unit["padded"])))
            self.states.append(tuple(
                NDArray(x) for x in self._unit_state_leaves(trainer, unit)))

    # ---------------- layout helpers ----------------
    def _flat_shard(self, flat, padded: int):
        n = int(flat.shape[0])
        if n != padded:
            flat = jnp.pad(flat, (0, padded - n))
        return jax.device_put(flat, self.shard)

    def _unit_state_leaves(self, trainer, unit):
        """Create (or adopt from the Updater) each member's optimizer
        state, then concatenate + pad + shard per state slot."""
        opt = trainer._optimizer
        params = trainer._params
        per_member = []
        for j, shape in zip(unit["members"], unit["shapes"]):
            p = params[j]
            src = NDArray(jnp.asarray(p._data._data, jnp.float32)) \
                if unit["mp"] else p.data()
            st = trainer._updater.states.get(j)
            if not (isinstance(st, tuple)
                    and all(isinstance(s, NDArray)
                            and tuple(s.shape) == shape for s in st)):
                st = opt.create_state(j, src)
            per_member.append(tuple(s._data.reshape(-1) for s in st))
        counts = {len(m) for m in per_member}
        if len(counts) != 1:
            raise MXNetError(
                "zero-shard: optimizer state leaf count differs across "
                f"bucket members ({sorted(counts)})")
        leaves = []
        for li in range(counts.pop()):
            flats = [m[li] for m in per_member]
            flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
            leaves.append(self._flat_shard(flat, unit["padded"]))
        return leaves

    # ---------------- per-step host work ----------------
    def pack_hparams(self, opt, lrs, wds, ts):
        """Per-unit hyperparameters: scalars for single-param units,
        per-element packed vectors for buckets."""
        ulrs, uwds, uts = [], [], []
        for unit in self.units:
            m = unit["members"]
            if len(m) == 1:
                ulrs.append(onp.float32(lrs[m[0]]))
                uwds.append(onp.float32(wds[m[0]]))
                uts.append(onp.int32(ts[m[0]]))
            else:
                lv, wv, tv = opt.pack_shard_hparams(
                    lrs, wds, ts, list(m), list(unit["sizes"]),
                    unit["padded"])
                ulrs.append(lv)
                uwds.append(wv)
                uts.append(tv)
        return tuple(ulrs), tuple(uwds), tuple(uts)

    def place_leaf(self, d):
        return _place_on_mesh(self.mesh, self.axis, d)

    # ---------------- observability ----------------
    @staticmethod
    def _per_replica_bytes(a) -> int:
        """Addressable-shard bytes — delegates to the ONE accounting
        helper the buffer census uses (telemetry/memory.py), so
        ``state_bytes_per_replica`` and the census ``optimizer`` pool
        agree byte-for-byte by construction."""
        from ..telemetry.memory import device_bytes
        return device_bytes(a)

    def state_bytes_per_replica(self) -> int:
        """PER-REPLICA bytes of the sharded state + masters; every
        buffer walked is (re-)filed in the census ``optimizer`` pool —
        the walk IS the registration (one accounting path)."""
        c = _telemetry().memory.census()
        total = 0
        for st in self.states:
            for s in st:
                c.register("optimizer", s)
                total += self._per_replica_bytes(s._data)
        for m in self.masters:
            c.register("optimizer", m)
            total += self._per_replica_bytes(m._data)
        return total


def _infer_batch_size(traced) -> int:
    for leaf in traced:
        d = leaf._data if isinstance(leaf, NDArray) else leaf
        if getattr(d, "ndim", 0) >= 1:
            return int(d.shape[0])
    return 1


class CompiledTrainStep:
    """One callable = one full training step, compiled.

    Built by ``Trainer.compile_step(loss_fn)``. ``loss_fn(*batch)`` is
    ordinary imperative Gluon code returning a loss NDArray; calling the
    step runs forward+backward+allreduce+update and returns the loss.
    Gradient semantics match ``loss.backward()`` (seed ones — the summed
    loss is differentiated) followed by ``trainer.step(batch_size)``.
    """

    def __init__(self, trainer, loss_fn: Callable, donate: bool = True,
                 train_mode: bool = True, zero_shard: Optional[bool] = None,
                 zero_axis: str = "dp", mesh=None,
                 analyze: Optional[str] = None,
                 numerics: Optional[str] = None,
                 autotune: Optional[str] = None):
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._donate = donate
        self._train = train_mode
        # self-tuning autopilot (docs/PERF_NOTES.md "Autotuner"):
        # None = the MXNET_AUTOTUNE env gate; 'off'|'cached'|'on'
        # explicit. Tuning runs ONCE, on the first step call (a real
        # batch pins the shape bucket), BEFORE the live program builds
        # so the winning config governs it.
        self._autotune = autotune
        self._autotune_done = False
        self._autotune_outcome = None
        self._mode: Optional[str] = None   # None→undecided, 'fused'|'eager'
        self._lru: "OrderedDict[Any, dict]" = OrderedDict()
        self._trace_signatures: set = set()
        self._sig_history: list = []   # bucket keys in trace order
        self._n_traces = 0
        self._steps_done = 0
        # opt-in program lint after the first step (docs/ANALYSIS.md);
        # default comes from MXNET_ANALYSIS
        self._analyze = _analysis_mode(analyze)
        self._analysis_report = None
        # in-program numerics instrumentation (docs/OBSERVABILITY.md
        # "numerics"): None | 'global' | 'per_layer'; default from
        # MXNET_NUMERICS. Part of the bucket signature — switching mode
        # compiles a fresh instrumented program.
        self._numerics = _telemetry().numerics.mode(numerics)
        self._pending_numerics = None
        # ZeRO-1 sharded update: None = auto (on when a mesh with a
        # `zero_axis` axis is active), True = required, False = off
        self._zero_requested = zero_shard
        self._zero_axis = zero_axis
        self._zero_mesh = mesh
        self._zero_ok: Optional[tuple] = None   # (mesh, axis) once decided
        self._zero: Optional[_ZeroShardPlan] = None
        self._census_done = False
        self._plain_mesh: Optional[tuple] = None  # mesh-aware plain mode
        self._mesh_prepared = False

        # dedup while preserving order: tied params may appear twice in a
        # collected dict; bind each object once
        seen: set = set()
        self._all_params = []
        for p in trainer._all_params:
            if id(p) not in seen:
                seen.add(id(p))
                self._all_params.append(p)
        pos = {id(p): i for i, p in enumerate(self._all_params)}
        # trainer._params (grad_req != null) carry the optimizer indices
        self._trainable_pos = [pos[id(p)] for p in trainer._params]
        # the checkpoint stack finds zero-sharded state through this
        trainer._register_compiled(self)

    # ---------------- introspection ----------------
    @property
    def n_traces(self) -> int:
        """Distinct compiled step programs built so far (the retrace
        counter tests assert on — trace-time side effect, stable under
        jit-cache eviction)."""
        return self._n_traces

    @property
    def mode(self) -> Optional[str]:
        return self._mode

    @property
    def zero_sharded(self) -> bool:
        """True when the ZeRO-1 sharded weight update is active."""
        return self._zero is not None or self._zero_ok is not None

    @property
    def analysis_report(self):
        """The ProgramReport from the last opt-in ``analyze=`` run (or
        ``None``)."""
        return self._analysis_report

    # ---------------- numerics instrumentation ----------------
    @property
    def numerics(self) -> Optional[str]:
        """Active numerics mode: None (off) | 'global' | 'per_layer'."""
        return self._numerics

    def set_numerics(self, mode: Optional[str]):
        """Switch the numerics instrumentation mode ('off'/None,
        'global', 'per_layer'). The mode is part of the bucket
        signature, so the next call compiles a fresh program for its
        shape bucket; existing buckets stay cached."""
        self._numerics = _telemetry().numerics.mode(mode or "off")

    def take_numerics(self):
        """Pop the :class:`~mxnet_tpu.telemetry.StepNumerics` record of
        the most recent step (None when numerics is off). The TrainLoop
        pushes this into the dispatch window alongside the loss so the
        statistics are read sync-free at the blessed retire; windowless
        callers can hand it to ``telemetry.numerics.monitor()`` or read
        :meth:`numerics_values` directly."""
        rec, self._pending_numerics = self._pending_numerics, None
        return rec

    def numerics_values(self) -> Optional[dict]:
        """Convenience synchronous read of the last step's numerics:
        pops the pending record, publishes it through the monitor
        (gauges + divergence anomalies + forensics, as a window retire
        would), and returns the host values dict — or None when
        numerics is off / no step ran. This BLOCKS on the step's
        program; prefer the TrainLoop's window path in hot loops."""
        rec = self.take_numerics()
        if rec is None:
            return None
        return _telemetry().numerics.monitor().observe_retire(
            self._steps_done, rec)

    def explain_retrace(self) -> str:
        """WHY the most recent retrace happened: a component-wise diff
        of the last two program cache keys (shape-bucket signatures) —
        new traced shapes/dtypes, changed static argument values,
        changed argument structure (analysis/program.py)."""
        if not self._sig_history:
            return "no program traced yet"
        if len(self._sig_history) < 2:
            return "only one program traced (no retrace to explain)"
        from ..analysis.program import explain_signature_diff
        return explain_signature_diff(self._sig_history[-2],
                                      self._sig_history[-1])

    def input_placement(self) -> Optional[Callable]:
        """The host→device placement this step applies to its input
        leaves: ``place(x)`` device_puts a raw array with the step's
        exact ``NamedSharding`` (dp-sharded batch on a mesh, replicated
        otherwise), or ``None`` when the step runs single-device (plain
        default-device placement suffices). The device prefetcher
        (``gluon.data.DevicePrefetcher`` / ``TrainLoop.prefetch``) stages
        upcoming batches through this so the host→device copy overlaps
        the previous step's compute instead of serializing inside jit
        dispatch."""
        from ..parallel.mesh import current_mesh, place_on_mesh
        mesh = axis = None
        if self._zero_ok is not None:
            mesh, axis = self._zero_ok
        elif self._plain_mesh is not None:
            mesh, axis = self._plain_mesh
        else:
            m = self._zero_mesh or current_mesh()
            a = self._zero_axis
            if m is not None and a in m.axis_names \
                    and m.shape[a] >= 2:
                mesh, axis = m, a
        if mesh is None:
            return None
        return lambda d, _m=mesh, _a=axis: place_on_mesh(_m, _a, d)

    def optimizer_state_bytes(self) -> int:
        """PER-REPLICA bytes of optimizer state (momenta/moments + fp32
        master copies). Under the ZeRO-1 sharded mode each replica holds
        1/N of every state buffer; in the plain fused and eager modes
        state is fully replicated — the ratio between the two is the
        memory the sharded update frees (~N× for Adam). Accounting is
        the census's own ``telemetry.memory.device_bytes`` and every
        buffer walked is (re-)filed in the census ``optimizer`` pool,
        so this number and ``census().live_bytes_by_pool()['optimizer']``
        agree byte-for-byte (tests/test_memory.py pins it)."""
        if self._zero is not None:
            return self._zero.state_bytes_per_replica()
        c = _telemetry().memory.census()
        total = 0
        for st in self._trainer._updater.states.values():
            for s in jax.tree_util.tree_leaves(
                    st, is_leaf=lambda x: isinstance(x, NDArray)):
                if isinstance(s, NDArray):
                    c.register("optimizer", s)
                    total += _ZeroShardPlan._per_replica_bytes(s._data)
        return total

    def memory_report(self, *args, batch_size: Optional[int] = None,
                      **kwargs):
        """Static HBM footprint of the compiled step program
        (:class:`~mxnet_tpu.telemetry.MemoryReport`): per shape-bucket
        ``memory_analysis()`` — argument/output/temp/generated-code
        bytes, donated alias bytes, peak estimate.

        With a batch: that bucket's report (lower+compile once, cached
        on the bucket entry; the AOT executable is reused when
        :meth:`aot_compile` already built it). With NO arguments: the
        field-wise max over every bucket analyzed so far (buckets run
        one at a time, so the worst bucket is the run's headroom), or
        ``None`` when none was. Eager mode: ``None`` — there is no
        compiled program to attribute. Split (dist-store) mode covers
        the grad program only. Each report also refreshes the
        ``mx_hbm_compiled_bytes{component}`` / ``mx_hbm_peak_estimate_
        bytes`` gauges and registers with the OOM forensics, so a
        post-mortem dump names every bucket's static peak."""
        t = _telemetry()
        if not args and not kwargs:
            reports = [e["memory"] for e in self._lru.values()
                       if e.get("memory") is not None]
            return t.memory.MemoryReport.merge(reports) if reports \
                else None
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode != "fused":
            return None
        entry, _ = self._entry_for(args, kwargs)
        if entry.get("memory") is not None:
            return entry["memory"]
        compiled = entry.get("exe")
        if compiled is None:
            info = self.lower_entry(*args, batch_size=batch_size,
                                    **kwargs)
            if info is None:
                return None
            compiled = info["lowered"].compile()
        report = t.memory.MemoryReport.from_compiled(compiled)
        entry["memory"] = report
        n_buckets = sum(1 for e in self._lru.values()
                        if e.get("memory") is not None)
        t.memory.register_compiled_report(
            f"{self._mode}:bucket{n_buckets}", report)
        self._publish_hbm()
        return report

    def _publish_hbm(self):
        """``mx_hbm_*`` gauges = field-wise max over analyzed buckets."""
        t = _telemetry()
        reports = [e["memory"] for e in self._lru.values()
                   if e.get("memory") is not None]
        if not reports:
            return
        merged = t.memory.MemoryReport.merge(reports)
        reg = t.registry()
        g = reg.gauge(t.names.HBM_COMPILED_BYTES)
        for field in merged.FIELDS:
            g.set(getattr(merged, field),
                  label=field.replace("_bytes", ""))
        reg.gauge(t.names.HBM_PEAK_BYTES).set(merged.peak_bytes)

    def _register_census(self):
        """File the step's long-lived device buffers in the live-buffer
        census (telemetry/memory.py): parameters under ``params``,
        optimizer state/masters under ``optimizer``. Weakref-based and
        idempotent — one call after the first step covers the whole run
        because writeback rebinds ``_data`` INSIDE the same handles."""
        try:
            c = _telemetry().memory.census()
            for p in self._all_params:
                if p._data is not None:
                    c.register("params", p._data)
            if self._zero is not None:
                self._zero.state_bytes_per_replica()   # registers
            else:
                for st in self._trainer._updater.states.values():
                    for s in jax.tree_util.tree_leaves(
                            st, is_leaf=lambda x: isinstance(x, NDArray)):
                        if isinstance(s, NDArray):
                            c.register("optimizer", s)
        except Exception:        # pragma: no cover - census must never
            _LOG.debug("census registration failed", exc_info=True)
            return                  # kill a step; retry next call
        self._census_done = True

    # ---------------- mode decision ----------------
    def _decide_mode(self) -> str:
        tr = self._trainer
        if not tr._kv_initialized:
            # single-process in-program stores need no kvstore at all —
            # seeding one would alias param buffers that donation later
            # invalidates. Dist stores DO need init (for pushpull_list).
            kind = tr._kvstore_kind
            needs_kv = kind is not None and (
                not isinstance(kind, str) or "dist" in kind)
            if needs_kv:
                tr._init_kvstore()
            else:
                tr._update_on_kvstore = False
        if tr._update_on_kvstore:
            return "eager"   # optimizer lives on the store: cannot fuse
        for p in self._all_params:
            if p._data is None:
                return "eager"   # deferred shapes: eager forward infers
            if p.stype != "default" or p._grad_stype != "default":
                return "eager"   # sparse storage/grad: lazy row path
        zero = self._resolve_zero()
        opt = self._trainer._optimizer
        if not zero and opt.multi_precision and any(
                p._data._data.dtype in (jnp.float16, jnp.bfloat16)
                for p in self._trainer._params):
            # master-weight states fuse only via the sharded update
            # (the zero plan owns flat fp32 masters); plain mode: eager
            return "eager"
        return "fused"

    def _resolve_zero(self) -> bool:
        """Decide whether the ZeRO-1 sharded update applies: a mesh with
        the dp axis must be active, the optimizer rule elementwise, and
        the kvstore's reduction must both live in-program AND advertise
        the reduce-scatter decomposition. A valid mesh whose update is
        gated off (opt-out, non-elementwise optimizer) still runs the
        PLAIN fused mode mesh-aware — params replicated, batch sharded,
        psum in-program."""
        from ..parallel.mesh import current_mesh
        mesh = self._zero_mesh or current_mesh()
        axis = self._zero_axis
        mesh_ok = (mesh is not None and axis in mesh.axis_names
                   and mesh.shape[axis] >= 2)
        if mesh_ok:
            self._plain_mesh = (mesh, axis)
        reason = None
        if self._zero_requested is False:
            return False
        if not mesh_ok:
            reason = f"no active mesh with a {axis!r} axis of size >= 2"
        else:
            opt = self._trainer._optimizer
            kv = self._trainer._kvstore
            if not getattr(opt, "elementwise_update", False):
                reason = (f"{type(opt).__name__} update is not elementwise "
                          "(cannot run on flat shards)")
            elif self._host_allreduce():
                reason = "kvstore reduction cannot live in-program"
            elif kv is not None and not getattr(
                    kv, "in_program_reduce_scatter", True):
                reason = "kvstore does not advertise the reduce-scatter path"
        if reason is not None:
            if self._zero_requested:
                raise MXNetError(f"compile_step(zero_shard=True): {reason}")
            return False
        self._zero_ok = (mesh, axis)
        return True

    def _host_allreduce(self) -> bool:
        kv = self._trainer._kvstore
        # unknown custom stores default to the conservative host path
        return kv is not None and not getattr(kv, "in_program_reduce",
                                              False)

    # ---------------- call ----------------
    def __call__(self, *args, batch_size: Optional[int] = None, **kwargs):
        # the whole step is a transfer-guard hot region: with
        # MXNET_TRANSFER_GUARD=log|raise any device->host sync in here —
        # a .asnumpy() in the loss_fn concretizing the trace, a silent
        # per-step sync on the eager fallback — logs its stack or raises
        with _tguard.hot_scope("CompiledTrainStep.step"):
            # device-lost seam (elastic/detect.py), alongside the OOM
            # seams inside _guarded_call: an escaping PjRt device loss
            # gets exactly one device_lost anomaly before it propagates
            with _edetect().device_lost_guard(
                    "CompiledTrainStep.step (compile/dispatch)",
                    step=self._steps_done + 1):
                # chaos-harness seam bracketing step dispatch — OUTSIDE
                # the first-call eager fallback (_guarded_call's try),
                # so an injected loss propagates to the elastic
                # supervisor instead of demoting the program to eager
                fault_point("step.dispatch", "before")
                out = self._guarded_call(args, kwargs, batch_size)
                fault_point("step.dispatch", "after")
        if self._analyze is not None and self._analysis_report is None:
            self._run_analysis(args, kwargs, batch_size)
        return out

    @property
    def autotune_result(self):
        """The :class:`~mxnet_tpu.tuning.AutotuneOutcome` of this
        step's tuning pass (None until the first call; mode 'off'
        produces an off-outcome stub). The bench legs attach its
        ``bench_dict()`` next to the kernel/fusion posture."""
        return self._autotune_outcome

    def autotune(self, *args, batch_size: Optional[int] = None,
                 mode: Optional[str] = None, **kwargs):
        """Explicitly tune this step for the shape bucket ``args`` pin
        (normally implicit on the first call when
        ``compile_step(autotune=)``/``MXNET_AUTOTUNE`` arms it).
        Returns the outcome; winners apply as tuned overrides and,
        after a search, persist to ``MXNET_AUTOTUNE_CACHE``."""
        from .. import tuning as _tuning
        self._autotune_done = True
        self._autotune_outcome = _tuning.tune_step(
            self, args, kwargs, batch_size=batch_size,
            mode=mode if mode is not None else self._autotune)
        return self._autotune_outcome

    def _maybe_autotune(self, args, kwargs, batch_size):
        """First-call tuning hook. Never kills a step — a tuner bug
        costs the tuned config, not the run. Runs under
        ``allow_transfers``: tuning is a designed offline measurement
        phase, not a hot-loop sync."""
        self._autotune_done = True
        from .. import tuning as _tuning
        if _tuning.autotune_mode(self._autotune) == "off":
            self._autotune_outcome = _tuning.AutotuneOutcome(
                "off", "off")
            return
        try:
            with _tguard.allow_transfers("autotune measurement"):
                self._autotune_outcome = _tuning.tune_step(
                    self, args, kwargs, batch_size=batch_size,
                    mode=self._autotune)
        except Exception as e:   # pragma: no cover - defensive
            _LOG.warning("compile_step: autotune failed (%s: %s); "
                         "running with defaults", type(e).__name__, e)

    def _guarded_call(self, args, kwargs, batch_size):
        if not self._autotune_done and not self._steps_done:
            self._maybe_autotune(args, kwargs, batch_size)
        if self._mode is None:
            self._mode = self._decide_mode()
        t = _telemetry()
        if self._mode == "eager":
            if self._numerics:
                _LOG.warning(
                    "compile_step: numerics instrumentation requires "
                    "the fused path (this program runs eager); disabled"
                    " — MXNET_INSPECT_NAN=1 is the eager-mode guard")
                self._numerics = None
            with t.memory.oom_guard("CompiledTrainStep.step (eager)",
                                    step=self._steps_done + 1):
                out = self._eager_call(args, kwargs, batch_size)
            if not self._census_done:
                self._register_census()
            return out
        opt = self._trainer._optimizer
        # first call: the trace may fail AFTER hyperparameter counts were
        # advanced — snapshot so the eager fallback replays step 1 as
        # step 1 (Adam's bias correction depends on t)
        snapshot = (opt.num_update, dict(opt._index_update_count)) \
            if not self._steps_done else None
        try:
            # the OOM seam: a RESOURCE_EXHAUSTED at compile or dispatch
            # writes its ranked post-mortem BEFORE the fallback/raise
            # machinery sees it (telemetry/memory.py)
            with t.memory.oom_guard("CompiledTrainStep.step (compile/"
                                    "dispatch)",
                                    step=self._steps_done + 1):
                out = self._fused_call(args, kwargs, batch_size)
        except Exception as e:
            if self._steps_done:
                raise   # the program is proven; this is a genuine error
            _LOG.warning(
                "compile_step: fused trace failed (%s: %s); falling back "
                "to the eager tape path", type(e).__name__, e)
            opt.num_update, opt._index_update_count = \
                snapshot[0], snapshot[1]
            self._mode = "eager"
            return self._eager_call(args, kwargs, batch_size)
        self._steps_done += 1
        if not self._census_done:
            self._register_census()
        return out

    step = __call__

    def _run_analysis(self, args, kwargs, batch_size):
        """Post-first-step program lint (``analyze=``/MXNET_ANALYSIS):
        'report' stores the ProgramReport, 'warn' also logs findings,
        'raise' raises on error-severity findings."""
        from ..analysis import program as _aprog
        from ..analysis.lint import lint_function
        try:
            report = _aprog.analyze_step(self, *args,
                                         batch_size=batch_size, **kwargs)
        except MXNetError:
            raise
        except Exception as e:   # analysis must not kill a healthy run
            _LOG.warning("compile_step: program analysis failed "
                         "(%s: %s); skipping", type(e).__name__, e)
            self._analysis_report = False
            return
        try:
            # the source lint explains WHY a step fell back to eager
            # (the .asnumpy() line) alongside the program findings
            report.findings.extend(lint_function(self._loss_fn))
        except Exception:        # pragma: no cover - defensive
            pass
        self._analysis_report = report
        if self._analyze == "warn" and not report.ok:
            _LOG.warning("compile_step program analysis:\n%s",
                         report.summary())
        elif self._analyze == "raise":
            report.raise_if_findings()

    # ---------------- eager fallback ----------------
    def _eager_call(self, args, kwargs, batch_size):
        from .. import autograd
        wrap = lambda a: a if isinstance(a, NDArray) or not isinstance(
            a, (onp.ndarray, jax.Array)) else NDArray(a)   # noqa: E731
        args = tuple(wrap(a) for a in args)
        kwargs = {k: wrap(v) for k, v in kwargs.items()}
        with autograd.record(train_mode=self._train):
            loss = self._loss_fn(*args, **kwargs)
        _tape.backward([loss])
        if batch_size is None:
            batch_size = _infer_batch_size(
                [a for a in args if isinstance(a, NDArray)])
        self._trainer.step(batch_size)
        self._steps_done += 1
        return loss

    # ---------------- fused path ----------------
    def _flatten(self, args, kwargs):
        all_leaves, arg_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda t: isinstance(t, NDArray))
        traced = [l for l in all_leaves if isinstance(l, _ARRAY_TYPES)]
        static_spec = tuple(_TRACED if isinstance(l, _ARRAY_TYPES) else l
                            for l in all_leaves)
        nd_mask = tuple(isinstance(l, NDArray) for l in traced)
        return traced, arg_treedef, static_spec, nd_mask

    @staticmethod
    def _cache_cap() -> int:
        try:
            return int(os.environ.get("MXNET_FUSED_STEP_CACHE_SIZE", "0"))
        except ValueError:
            return 0

    def _entry_for(self, args, kwargs):
        traced, arg_treedef, static_spec, nd_mask = self._flatten(
            args, kwargs)
        shapes = tuple(
            (tuple((l._data if isinstance(l, NDArray) else l).shape),
             str((l._data if isinstance(l, NDArray) else l).dtype))
            for l in traced)
        sig = (self._train, arg_treedef, static_spec, nd_mask, shapes,
               self._numerics)
        entry = self._lru.get(sig)
        if entry is None:
            entry = self._build_bucket(arg_treedef, static_spec, nd_mask)
            t = _telemetry()
            t.registry().counter(t.names.COMPILE_RETRACES).inc()
            self._lru[sig] = entry
            self._trace_signatures.add(sig)
            self._sig_history.append(sig)
            cap = self._cache_cap()
            while cap > 0 and len(self._lru) > cap:
                self._lru.popitem(last=False)
        else:
            self._lru.move_to_end(sig)
        return entry, traced

    def _build_bucket(self, arg_treedef, static_spec, nd_mask) -> dict:
        params = self._all_params
        loss_fn = self._loss_fn
        train = self._train
        t_pos = tuple(self._trainable_pos)
        opt_fn = self._trainer._optimizer.fused_step_fn()
        donate = (0, 1) if self._donate else ()
        step_self = self

        # in-program numerics aux (docs/OBSERVABILITY.md "numerics"):
        # scalar reductions of values the program already computes —
        # the update dataflow itself is untouched, so numerics=on is
        # bit-exact on params/loss vs off
        numerics = self._numerics
        if numerics and self._host_allreduce():
            _LOG.warning(
                "compile_step: numerics instrumentation is not wired "
                "for the split (host-allreduce) mode; disabled")
            numerics = None
        nxm = _telemetry().numerics if numerics else None
        if numerics:
            # trainable-param dtypes are static at build time (fused
            # mode guarantees materialized shapes)
            grad_dtype_groups: "dict[str, list]" = {}
            for j, p in enumerate(self._trainer._params):
                grad_dtype_groups.setdefault(
                    str(p._data._data.dtype), []).append(j)

        def run_loss(pds, traced_leaves, key):
            it = iter(NDArray(l) if m else l
                      for l, m in zip(traced_leaves, nd_mask))
            leaves = [next(it) if s is _TRACED else s for s in static_spec]
            args, kwargs = jax.tree_util.tree_unflatten(arg_treedef, leaves)
            binding = ParamBinding(params, pds)
            push_trace_key(key)
            prev_r = _tape.set_recording(False)
            prev_s = _tape.set_taping_suspended(True)
            prev_t = _tape.set_training(train)
            try:
                with binding:
                    out = loss_fn(*args, **kwargs)
            finally:
                _tape.set_recording(prev_r)
                _tape.set_taping_suspended(prev_s)
                _tape.set_training(prev_t)
                pop_trace_key()
            l = out._data if isinstance(out, NDArray) else jnp.asarray(out)
            # differentiate the SUM: identical to loss.backward() seeding
            # ones over the per-sample loss vector
            return jnp.sum(l), (l, binding.state)

        def grad_part(pds, traced_leaves, key):
            (_, (l, state)), grads = jax.value_and_grad(
                run_loss, has_aux=True)(tuple(pds), traced_leaves, key)
            gs = tuple(grads[i] for i in t_pos)
            return l, state, gs

        if self._zero is not None:
            # ZeRO-1 sharded update: grads constrained to the flat
            # 1/N-per-replica layout (XLA converts the allreduce into a
            # reduce-scatter feeding it), elementwise rule on each
            # replica's shard against permanently-sharded state, new
            # weights constrained back to replicated (all-gather).
            # The elementwise rule goes through the Pallas fused
            # multi-tensor update kernel when the MXNET_PALLAS gate
            # selects it (ops/kernels/opt_update.py; bit-exact vs the
            # XLA chain, pinned by tests) — one kernel per flat unit
            # instead of a per-op elementwise chain.
            try:
                from ..ops.kernels.opt_update import \
                    kernel_step_fn as _opt_kfn
                opt_kernel_fn = _opt_kfn(self._trainer._optimizer)
            except Exception:   # kernel layer must never kill a step
                _LOG.debug("opt-update kernel unavailable",
                           exc_info=True)
                opt_kernel_fn = None
            if opt_kernel_fn is not None:
                opt_fn = opt_kernel_fn
            plan = self._zero
            shard, repl = plan.shard, plan.repl
            units = plan.units
            mslot = plan.master_slot
            wsc = jax.lax.with_sharding_constraint

            def _flat_cat(arrs):
                flats = [a.reshape(-1) for a in arrs]
                return flats[0] if len(flats) == 1 \
                    else jnp.concatenate(flats)

            def _padded(v, padded):
                n = v.shape[0]
                return v if n == padded else jnp.pad(v, (0, padded - n))

            # comm bucketing (docs/PERF_NOTES.md "Communication
            # overlap"): the flat units are grouped into size-bounded
            # buckets in reverse-topological grad order and each bucket
            # concatenates into ONE reduce-scatter / shard update / ONE
            # all-gather (parallel/collectives.py). Overlap then falls
            # out of real data dependencies — bucket k's collectives
            # depend only on bucket k's units, so other buckets'
            # backward/update compute is free to hide the wire time —
            # with nothing for XLA's simplifier or scheduler to defeat
            # (barriers and value-ties both die before the final
            # schedule). Per-unit elementwise math is untouched and the
            # packing is pure routing, so ANY bucketing (including the
            # serial single-bucket baseline) is bit-exact vs any other.
            bucket_bytes = _zero_bucket_bytes()
            buckets = zero_bucket_schedule(units, bucket_bytes)
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel.collectives import allgather_bucketed
            nsh = plan.n_shards
            shard2d = NamedSharding(
                plan.mesh.mesh, PartitionSpec(plan.axis, None))

            def _unpack_bucket(buf, idx):
                """Per-unit padded flats out of an interleaved
                (n_shards, S) bucket buffer — comm-free slices on the
                free axis whether the buffer is sharded or replicated
                (parallel/collectives.py layout)."""
                outs, off = [], 0
                for k in idx:
                    s = units[k]["padded"] // nsh
                    outs.append(buf[:, off:off + s].reshape(
                        units[k]["padded"]))
                    off += s
                return outs

            def _scatter_members(dst, k, flat, to_pds=True):
                """Write unit k's member views of a flat buffer into
                ``dst`` — at the param positions (``to_pds``) or at the
                trainable-slot positions."""
                u = units[k]
                off = 0
                for j, shp, n in zip(u["members"], u["shapes"],
                                     u["sizes"]):
                    dst[t_pos[j] if to_pds else j] = \
                        flat[off:off + n].reshape(shp)
                    off += n

            def pack_buckets(pds):
                """Per-bucket interleaved (n_shards, S) forward weight
                buffers (forward dtype — buckets are dtype-uniform)."""
                bufs = []
                for idx in buckets:
                    rows = [
                        _padded(_flat_cat(
                            [pds[t_pos[j]]
                             for j in units[k]["members"]]),
                            units[k]["padded"]).reshape(
                                nsh, units[k]["padded"] // nsh)
                        for k in idx]
                    buf = rows[0] if len(rows) == 1 \
                        else jnp.concatenate(rows, axis=1)
                    # pin the PRIMAL pack replicated: the params are
                    # already replicated, so re-materializing them in
                    # run_loss_bufs must stay comm-free slicing.
                    # Without the pin GSPMD may shard the pack (its
                    # cotangent wants P(axis)) and then pay per-param
                    # gather chains to rebuild the forward weights
                    bufs.append(wsc(buf, repl))
                return tuple(bufs)

            def run_loss_bufs(bufs, pds, traced_leaves, key):
                """run_loss with the trainable params re-materialized
                from the packed bucket buffers.  Differentiating w.r.t.
                ``bufs`` (not ``pds``) makes autodiff ACCUMULATE each
                bucket's gradient into one flat packed buffer, so the
                pending cross-replica sum covers the whole bucket and
                GSPMD lowers it as ONE reduce-scatter per bucket —
                reducing per-param grads first and concatenating after
                would materialize one collective per unit instead."""
                pds = list(pds)
                for bi, idx in enumerate(buckets):
                    for k, flat in zip(idx,
                                       _unpack_bucket(bufs[bi], idx)):
                        _scatter_members(pds, k, flat)
                return run_loss(tuple(pds), traced_leaves, key)

            def zero_fused(pds, sts, masters, traced_leaves, ulrs, uwds,
                           uts, rescale, clip, key):
                step_self._n_traces += 1
                (_, (l, state)), grad_bufs = jax.value_and_grad(
                    run_loss_bufs, has_aux=True)(
                        pack_buckets(pds), pds, traced_leaves, key)
                n_units = len(units)
                ws_u = [None] * n_units
                for k, u in enumerate(units):
                    if u["mp"]:
                        wflat = masters[mslot[k]]   # persistent fp32 shard
                    else:
                        wflat = wsc(_padded(_flat_cat(
                            [pds[t_pos[j]] for j in u["members"]]),
                            u["padded"]), shard)
                    ws_u[k] = wflat
                gs_u = [None] * n_units
                new_ws = [None] * n_units
                new_sts_u = [None] * n_units
                fulls = [None] * n_units
                for bi, idx in enumerate(buckets):
                    # ONE reduce-scatter for the whole bucket: the
                    # packed gradient buffer is a single pending
                    # cross-replica sum, and the shard2d constraint
                    # turns it into one collective whose per-unit
                    # shards slice out comm-free
                    gbuf = grad_bufs[bi]
                    upd = units[idx[0]]["upd_dtype"]
                    if gbuf.dtype != upd:
                        gbuf = gbuf.astype(upd)
                    # the constraint is applied to the FLAT view (row d
                    # of the interleaved layout = contiguous slice d of
                    # the flat buffer): GSPMD lowers a 1-D P(axis) pin
                    # on a pending sum as the clean reduce-scatter /
                    # all-reduce + partition-id-slice pattern the
                    # zero-dp program checks assert on
                    gbuf = wsc(gbuf.reshape(-1), shard).reshape(
                        nsh, -1)
                    b_gs = _unpack_bucket(gbuf, idx)
                    for k, g in zip(idx, b_gs):
                        gs_u[k] = g
                    bw, bst = opt_fn(
                        tuple(ws_u[k] for k in idx), tuple(b_gs),
                        tuple(ulrs[k] for k in idx),
                        tuple(uwds[k] for k in idx),
                        tuple(uts[k] for k in idx),
                        rescale, clip,
                        tuple(sts[k] for k in idx))
                    for k, w, st in zip(idx, bw, bst):
                        new_ws[k] = w
                        new_sts_u[k] = st
                    # ONE all-gather for the bucket's new weights.  The
                    # inner shard2d pin keeps the update output sharded
                    # so the `repl` constraint gathers the RESULT once —
                    # without it GSPMD propagates `repl` into the
                    # update's last elementwise op and all-gathers both
                    # of its operands instead
                    b_fulls = allgather_bucketed(
                        list(bw), nsh,
                        constrain=lambda b: wsc(wsc(b, shard2d), repl))
                    for k, f in zip(idx, b_fulls):
                        fulls[k] = f
                new_pds = list(state)
                new_masters = [None] * len(mslot)
                for k, u in enumerate(units):
                    full = fulls[k]
                    off = 0
                    for j, shp, n, dt in zip(u["members"], u["shapes"],
                                             u["sizes"], u["dtypes"]):
                        new_pds[t_pos[j]] = \
                            full[off:off + n].reshape(shp).astype(dt)
                        off += n
                    if u["mp"]:
                        new_masters[mslot[k]] = wsc(new_ws[k], shard)
                # pin the state outputs to the sharded layout: the
                # replicated all-gather consumer above must not make
                # GSPMD replicate the persistent buffers on the way out
                new_sts = tuple(tuple(wsc(s, shard) for s in st)
                                for st in new_sts_u)
                out = (tuple(new_pds), new_sts, tuple(new_masters), l)
                if numerics:
                    gs_log = ()
                    if numerics == "per_layer":
                        # logical per-param grads, sliced back out of
                        # the packed pre-scatter buffers (materializes
                        # the full gradient — the documented per-layer
                        # cost)
                        gs_log = [None] * len(t_pos)
                        for bi, idx in enumerate(buckets):
                            for k, flat in zip(
                                    idx, _unpack_bucket(grad_bufs[bi],
                                                        idx)):
                                _scatter_members(gs_log, k, flat,
                                                 to_pds=False)
                    out = out + (zero_aux(ws_u, gs_u, new_ws, gs_log,
                                          rescale),)
                return out

            def zero_aux(ws_u, gs_u, new_ws, gs, rescale):
                """Numerics aux from the flat 1/N-per-replica unit
                buffers: each sumsq/count is a shard-local reduction
                GSPMD psums on the dp axis, so every replica reports
                the exact GLOBAL statistic without materializing a
                replicated gradient (zero padding is finite/zero and
                never skews anything)."""
                r2 = jnp.square(jnp.asarray(rescale, jnp.float32))
                aux = {
                    "grad_sq": r2 * sum(nxm.sumsq(g) for g in gs_u),
                    "param_sq": sum(nxm.sumsq(w) for w in ws_u),
                    "upd_sq": sum(
                        nxm.sumsq(nw.astype(jnp.float32)
                                  - w.astype(jnp.float32))
                        for nw, w in zip(new_ws, ws_u)),
                }
                by_dt: "dict[str, list]" = {}
                for k, u in enumerate(units):
                    by_dt.setdefault(str(u["dtypes"][0]), []).append(k)
                aux["nonfinite"] = {
                    dt: sum(nxm.nonfinite_count(gs_u[k]) for k in ks)
                    for dt, ks in sorted(by_dt.items())}
                if numerics == "per_layer":
                    # per-parameter norms consume the LOGICAL grads —
                    # under ZeRO this can force XLA to materialize the
                    # full gradient it would otherwise reduce-scatter
                    # away (the documented per-layer cost)
                    aux["layer_grad_sq"] = jnp.stack(
                        [r2 * nxm.sumsq(g) for g in gs])
                drifts = []
                for k, u in enumerate(units):
                    if u["mp"]:
                        d = new_ws[k]
                        q = d.astype(u["dtypes"][0]).astype(jnp.float32)
                        drifts.append(jnp.max(
                            jnp.abs(d - q) / (jnp.abs(d) + 1e-8)))
                if drifts:
                    aux["master_drift"] = drifts[0] if len(drifts) == 1 \
                        else jnp.max(jnp.stack(drifts))
                return aux

            donate_z = (0, 1, 2) if self._donate else ()
            return {"kind": "zero",
                    "fn": jax.jit(zero_fused, donate_argnums=donate_z),
                    "exe": None, "flops": None, "numerics": numerics,
                    "probe": grad_part}

        if self._host_allreduce():
            # split mode (dist stores): program A computes loss+grads+
            # functional state; the kvstore's bucketed pushpull_list runs
            # between programs; program B is the donated fused update.
            grad_fn = jax.jit(grad_part)

            def update(ws, sts, lrs, wds, ts, rescale, clip, gs):
                step_self._n_traces += 1
                return opt_fn(ws, gs, lrs, wds, ts, rescale, clip, sts)

            return {"kind": "split", "grad": grad_fn,
                    "update": jax.jit(update, donate_argnums=donate),
                    "exe": None, "flops": None, "numerics": None,
                    "probe": grad_part}

        def fused_aux(ws, gs, new_ws, rescale):
            """Numerics aux for the plain fused modes: reductions of
            the grads/weights the update already holds. On a dp mesh
            (params replicated, batch sharded) GSPMD composes each
            reduction with the gradient psum, so the norms are global
            there too."""
            r2 = jnp.square(jnp.asarray(rescale, jnp.float32))
            gsq = [nxm.sumsq(g) for g in gs]
            aux = {
                "grad_sq": r2 * sum(gsq),
                "param_sq": sum(nxm.sumsq(w) for w in ws),
                "upd_sq": sum(
                    nxm.sumsq(nw.astype(jnp.float32)
                              - w.astype(jnp.float32))
                    for nw, w in zip(new_ws, ws)),
                "nonfinite": {
                    dt: sum(nxm.nonfinite_count(gs[j]) for j in js)
                    for dt, js in sorted(grad_dtype_groups.items())},
            }
            if numerics == "per_layer":
                aux["layer_grad_sq"] = jnp.stack([r2 * s for s in gsq])
            return aux

        def fused(pds, sts, traced_leaves, lrs, wds, ts, rescale, clip,
                  key):
            step_self._n_traces += 1
            l, state, gs = grad_part(pds, traced_leaves, key)
            ws = tuple(pds[i] for i in t_pos)
            new_ws, new_sts = opt_fn(ws, gs, lrs, wds, ts, rescale, clip,
                                     sts)
            new_pds = list(state)   # BN-stat rebinds + identity for rest
            for j, i in enumerate(t_pos):
                new_pds[i] = new_ws[j]
            out = (tuple(new_pds), new_sts, l)
            if numerics:
                out = out + (fused_aux(ws, gs, new_ws, rescale),)
            return out

        return {"kind": "fused",
                "fn": jax.jit(fused, donate_argnums=donate),
                "exe": None, "flops": None, "numerics": numerics,
                "probe": grad_part}

    def _ensure_states(self):
        updater = self._trainer._updater
        for i, p in enumerate(self._trainer._params):
            if i not in updater.states:
                updater.states[i] = \
                    self._trainer._optimizer.create_state_multi_precision(
                        i, p.data())
        return [updater.states[i]
                for i in range(len(self._trainer._params))]

    def _scalars(self, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        opt.rescale_grad = tr._scale / batch_size
        lrs, wds, ts = opt.begin_fused_step(
            list(range(len(tr._params))))
        rescale = onp.float32(opt.rescale_grad)
        clip = onp.float32(opt.clip_gradient
                           if opt.clip_gradient is not None else 0.0)
        return lrs, wds, ts, rescale, clip

    def _prepare_zero(self):
        """Materialize the zero plan: replicate weights on the mesh and
        build the flat sharded state/master buffers."""
        mesh, axis = self._zero_ok
        repl_sharding = mesh.sharding()
        for p in self._all_params:
            p._write_fused(jax.device_put(p._data._data, repl_sharding))
        self._zero = _ZeroShardPlan(self._trainer, mesh, axis)

    def _zero_call(self, entry, traced, batch_size):
        plan = self._zero
        pds = tuple(p._data._data for p in self._all_params)
        sts = tuple(tuple(s._data for s in st) for st in plan.states)
        masters = tuple(m._data for m in plan.masters)
        leaf_datas = tuple(plan.place_leaf(
            l._data if isinstance(l, NDArray) else l) for l in traced)
        lrs, wds, ts, rescale, clip = self._scalars(batch_size)
        ulrs, uwds, uts = plan.pack_hparams(self._trainer._optimizer,
                                            lrs, wds, ts)
        key = next_key()
        outs = entry["fn"](
            pds, sts, masters, leaf_datas, ulrs, uwds, uts, rescale, clip,
            key)
        if entry.get("numerics"):
            new_pds, new_sts, new_masters, l, auxd = outs
        else:
            new_pds, new_sts, new_masters, l = outs
            auxd = None
        # writeback: same handles, new buffers (donation contract); the
        # state/master handles stay sharded across steps
        for p, nw in zip(self._all_params, new_pds):
            p._write_fused(nw)
        for st, ns in zip(plan.states, new_sts):
            for s, n in zip(st, ns):
                s._data = n
        for m, nm in zip(plan.masters, new_masters):
            m._data = nm
        if auxd is not None:
            self._stash_numerics(entry, auxd, leaf_datas, batch_size,
                                 key)
        return NDArray(l)

    def _fused_call(self, args, kwargs, batch_size):
        if self._zero_ok is not None and self._zero is None:
            self._prepare_zero()
        elif self._plain_mesh is not None and not self._mesh_prepared:
            # mesh-aware PLAIN mode (zero gated off): params replicate on
            # the mesh so dp-sharded batches psum in-program
            mesh, _ = self._plain_mesh
            repl_sharding = mesh.sharding()
            for p in self._all_params:
                p._write_fused(jax.device_put(p._data._data, repl_sharding))
            self._mesh_prepared = True
        entry, traced = self._entry_for(args, kwargs)
        if batch_size is None:
            batch_size = _infer_batch_size(traced)
        if entry["kind"] == "zero":
            return self._zero_call(entry, traced, batch_size)
        states = self._ensure_states()
        for st in states:
            if not (isinstance(st, tuple) and all(
                    isinstance(s, NDArray) for s in st)):
                raise MXNetError(
                    "compile_step: optimizer state is not a flat NDArray "
                    "tuple (multi-precision?); eager path required")
        pds = tuple(p._data._data for p in self._all_params)
        sts = tuple(tuple(s._data for s in st) for st in states)
        leaf_datas = tuple(l._data if isinstance(l, NDArray) else l
                           for l in traced)
        if self._mesh_prepared:
            mesh, axis = self._plain_mesh
            leaf_datas = tuple(_place_on_mesh(mesh, axis, d)
                               for d in leaf_datas)
        lrs, wds, ts, rescale, clip = self._scalars(batch_size)
        key = next_key()

        if entry["kind"] == "split":
            l, state, gs = entry["grad"](pds, leaf_datas, key)
            # land gradients on the Parameter grad handles and reuse the
            # Trainer's own reduction machinery (bucketed pushpull_list)
            tr = self._trainer
            for p, g in zip(tr._params, gs):
                p.grad()._data = g
            tr._allreduce_grads()
            gs = tuple(p.grad()._data for p in tr._params)
            ws = tuple(pds[i] for i in self._trainable_pos)
            new_ws, new_sts = entry["update"](ws, sts, lrs, wds, ts,
                                              rescale, clip, gs)
            new_pds = list(state)
            for j, i in enumerate(self._trainable_pos):
                new_pds[i] = new_ws[j]
        else:
            fn = entry["exe"] or entry["fn"]
            outs = fn(pds, sts, leaf_datas, lrs, wds, ts,
                      rescale, clip, key)
            if entry.get("numerics"):
                new_pds, new_sts, l, auxd = outs
            else:
                (new_pds, new_sts, l), auxd = outs, None

        # writeback: same handles, new buffers (donation contract)
        for p, nw in zip(self._all_params, new_pds):
            p._write_fused(nw)
        for st, ns in zip(states, new_sts):
            for s, n in zip(st, ns):
                s._data = n
        if entry["kind"] != "split" and auxd is not None:
            self._stash_numerics(entry, auxd, leaf_datas, batch_size,
                                 key)
        return NDArray(l)

    # ---------------- numerics plumbing ----------------
    def _stash_numerics(self, entry, auxd, leaf_datas, batch_size, key):
        """Wrap this step's on-device aux in a StepNumerics record for
        the dispatch window: small device scalars (still async), the
        host-side lr/loss-scale context, and the one-shot NaN-origin
        forensic closure over the CAPTURED input batch + RNG key.
        Holding the leaf refs keeps at most window-depth input batches
        alive — the price of being able to replay the faulting batch.
        Must never kill a step."""
        t = _telemetry()
        try:
            rec = t.numerics.StepNumerics(
                mode=entry["numerics"], raw=auxd,
                param_names=self._numerics_param_names(),
                context=self._numerics_context(batch_size),
                forensic=self._make_forensic(entry, leaf_datas, key))
            self._pending_numerics = rec
        except Exception:        # pragma: no cover - defensive
            _LOG.warning("numerics stash failed", exc_info=True)

    def _numerics_param_names(self):
        """UNIQUE trainable-parameter names in trainer._params order:
        the collect_params dict keys where available (Parameter.name
        alone is 'weight'/'bias' and collides across blocks)."""
        names = getattr(self, "_numerics_names", None)
        if names is None:
            tr = self._trainer
            by_id = {id(p): n for p, n in zip(tr._all_params,
                                              tr._param_names)}
            names = [by_id.get(id(p), p.name) for p in tr._params]
            self._numerics_names = names
        return names

    def _numerics_context(self, batch_size):
        opt = self._trainer._optimizer
        ctx = opt.hparam_snapshot()
        ctx["batch_size"] = batch_size
        ctx["step_in_program"] = self._steps_done + 1
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        ctx["loss_scale"] = float(scaler.loss_scale) \
            if scaler is not None else None
        ctx["mode"] = "zero" if self._zero is not None else "fused"
        return ctx

    def _make_forensic(self, entry, leaf_datas, key):
        step_self = self

        def run(step_tag):
            return step_self._numerics_forensics(entry, leaf_datas, key,
                                                 step_tag)
        return run

    def _numerics_forensics(self, entry, leaf_datas, key, step_tag):
        """NaN-origin forensics, run ONCE per non-finite episode and
        OUTSIDE the hot loop (the monitor calls this under a blessed
        allow_transfers region when the ``nonfinite_grad`` anomaly
        fires): re-execute this bucket's loss+grad computation on the
        captured batch under ``jax.debug_nans``/``debug_infs`` to name
        the first non-finite-producing primitive, then once more plain
        (no donation) for the ranked per-layer norm table. Params are
        the CURRENT handles — the faulting step's pre-update weights
        were donated away — so the replay chases the batch, not the
        exact weight state (recorded in the dump)."""
        t = _telemetry()
        probe = entry.get("probe")
        if probe is None:
            return None
        pds = tuple(p._data._data for p in self._all_params)
        info = {"params_at": "retire (post-update handles)"}
        info["offending_op"] = t.numerics.localize_nonfinite(
            lambda: probe(pds, leaf_datas, key))
        try:
            l, _state, gs = jax.jit(probe)(pds, leaf_datas, key)
            lv = onp.asarray(l, dtype="float64")
            info["loss"] = float(lv.mean())
            layers = []
            for name, p, g in zip(self._numerics_param_names(),
                                  self._trainer._params, gs):
                ga = onp.asarray(jnp.asarray(g, jnp.float32),
                                 dtype="float64")
                nf = int((~onp.isfinite(ga)).sum())
                finite = ga[onp.isfinite(ga)]
                gnorm = float(onp.sqrt((finite ** 2).sum()))
                pa = onp.asarray(
                    jnp.asarray(p._data._data, jnp.float32),
                    dtype="float64")
                layers.append({
                    "param": name,
                    "shape": list(ga.shape),
                    "dtype": str(g.dtype),
                    "grad_norm": gnorm,
                    "param_norm": float(onp.linalg.norm(pa)),
                    "nonfinite": nf,
                })
            layers.sort(key=lambda d: (-d["nonfinite"], -d["grad_norm"]))
            info["layers"] = layers
        except Exception as e:
            info["reexec_error"] = f"{type(e).__name__}: {e}"
        return info

    # ---------------- program analysis (mx.analysis) ----------------
    def analyze(self, *args, batch_size: Optional[int] = None, **kwargs):
        """Run the program lint over this batch's shape bucket and
        return the :class:`~mxnet_tpu.analysis.ProgramReport` —
        collective census, donation audit, host transfers, dtype drift,
        fusion census (docs/ANALYSIS.md).  Does not advance optimizer
        counts."""
        from ..analysis.program import analyze_step
        return analyze_step(self, *args, batch_size=batch_size, **kwargs)

    def fusion_report(self, *args, batch_size: Optional[int] = None,
                      **kwargs):
        """Fusion census of this batch bucket's OPTIMIZED program
        (:class:`~mxnet_tpu.analysis.fusion.FusionReport`): every
        fusion/compute kernel with its op census, FLOP estimate and
        boundary bytes, the stranded-op ideal-fusion diff, and the
        compute-/memory-bound classification against the BENCH roofline
        ridge (docs/ANALYSIS.md "Fusion census").  ``None`` on the
        eager path — there is no compiled program to audit.  Cached
        with the bucket's :meth:`analyze` report."""
        report = self.analyze(*args, batch_size=batch_size, **kwargs)
        return getattr(report, "fusion", None)

    def sharding_report(self, *args, batch_size: Optional[int] = None,
                        **kwargs):
        """SPMD sharding audit of this batch bucket's OPTIMIZED program
        (:class:`~mxnet_tpu.analysis.sharding.ShardingAudit`): the
        per-buffer sharding table, implicit reshards ranked by wire
        bytes against this mode's spec pack, and the per-mesh-axis
        communication cost estimate (docs/ANALYSIS.md "Sharding
        analysis").  ``None`` on the eager path.  Cached with the
        bucket's :meth:`analyze` report."""
        report = self.analyze(*args, batch_size=batch_size, **kwargs)
        return getattr(report, "sharding", None)

    def lower_entry(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower this batch bucket's program for static analysis.

        Returns a dict with the ``jax.stages.Lowered``, the traced
        jaxpr, and the layout facts the checkers need (mesh/axis,
        expected donated buffer count, shard-unit sizes, blessed dtype
        conversions) — or ``None`` on the eager path, where there is no
        program to lower.  Live weights and optimizer counts are
        untouched; the retrace counter is restored (an analysis lower
        is not a training retrace).  Cached per bucket."""
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode != "fused":
            return None
        if self._zero_ok is not None and self._zero is None:
            self._prepare_zero()
        elif self._plain_mesh is not None and not self._mesh_prepared:
            mesh, _ = self._plain_mesh
            repl_sharding = mesh.sharding()
            for p in self._all_params:
                p._write_fused(jax.device_put(p._data._data,
                                              repl_sharding))
            self._mesh_prepared = True
        entry, traced = self._entry_for(args, kwargs)
        if entry.get("analysis") is not None:
            return entry["analysis"]
        if batch_size is None:
            batch_size = _infer_batch_size(traced)
        opt = self._trainer._optimizer
        n = len(self._trainer._params)
        blessed = []
        try:
            from .. import amp as _amp
            amp_on = _amp.is_enabled()
        except Exception:            # pragma: no cover - defensive
            amp_on = False
        if opt.multi_precision or amp_on:
            # the multi-precision master list: fp32 masters/islands are
            # the POINT of these modes, widening to f32 is intentional
            blessed = [("bfloat16", "float32"), ("float16", "float32")]
        rescale = onp.float32(1.0 / batch_size)
        clip = onp.float32(0.0)
        key = next_key()
        zeros = onp.zeros(n, onp.float32)
        ones = onp.ones(n, onp.int32)
        n_traces_before = self._n_traces
        try:
            if entry["kind"] == "zero":
                plan = self._zero
                pds = tuple(p._data._data for p in self._all_params)
                sts = tuple(tuple(s._data for s in st)
                            for st in plan.states)
                masters = tuple(m._data for m in plan.masters)
                leaf = tuple(plan.place_leaf(
                    l._data if isinstance(l, NDArray) else l)
                    for l in traced)
                ulrs, uwds, uts = plan.pack_hparams(opt, zeros, zeros,
                                                    ones)
                fargs = (pds, sts, masters, leaf, ulrs, uwds, uts,
                         rescale, clip, key)
                lowered = entry["fn"].lower(*fargs)
                jaxpr = self._safe_jaxpr(entry["fn"], fargs)
                n_state = sum(len(st) for st in sts)
                unit_sizes = sorted({u["padded"] for u in plan.units}
                                    | {u["total"] for u in plan.units})
                info = dict(
                    kind="zero", mode="zero", lowered=lowered,
                    jaxpr=jaxpr, mesh=plan.mesh, axis=plan.axis,
                    expected_donated=(len(pds) + n_state + len(masters))
                    if self._donate else None,
                    unit_sizes=unit_sizes, n_params=len(pds),
                    n_state_leaves=n_state, blessed_dtypes=blessed,
                    report=None)
            else:
                states = self._ensure_states()
                pds = tuple(p._data._data for p in self._all_params)
                sts = tuple(tuple(s._data for s in st) for st in states)
                leaf = tuple(l._data if isinstance(l, NDArray) else l
                             for l in traced)
                if self._mesh_prepared:
                    mesh, axis = self._plain_mesh
                    leaf = tuple(_place_on_mesh(mesh, axis, d)
                                 for d in leaf)
                if entry["kind"] == "split":
                    fargs = (pds, leaf, key)
                    lowered = entry["grad"].lower(*fargs)
                    jaxpr = self._safe_jaxpr(entry["grad"], fargs)
                    info = dict(kind="split", mode="split",
                                lowered=lowered, jaxpr=jaxpr, mesh=None,
                                axis=None, expected_donated=None,
                                unit_sizes=[], n_params=len(pds),
                                n_state_leaves=0,
                                blessed_dtypes=blessed, report=None)
                else:
                    fargs = (pds, sts, leaf, zeros, zeros, ones, rescale,
                             clip, key)
                    lowered = entry["fn"].lower(*fargs)
                    jaxpr = self._safe_jaxpr(entry["fn"], fargs)
                    mesh = axis = None
                    mode = "fused"
                    if self._mesh_prepared:
                        mesh, axis = self._plain_mesh
                        mode = "fused-mesh"
                    n_state = sum(len(st) for st in sts)
                    info = dict(
                        kind="fused", mode=mode, lowered=lowered,
                        jaxpr=jaxpr, mesh=mesh, axis=axis,
                        expected_donated=(len(pds) + n_state)
                        if self._donate else None,
                        unit_sizes=sorted({int(d.size) for d in pds}),
                        n_params=len(pds), n_state_leaves=n_state,
                        blessed_dtypes=blessed, report=None)
        finally:
            # lowering re-runs the traced python (n_traces side effect):
            # an analysis lower is not a training retrace
            self._n_traces = n_traces_before
        entry["analysis"] = info
        return info

    @staticmethod
    def _safe_jaxpr(fn, fargs):
        try:
            return jax.make_jaxpr(fn)(*fargs)
        except Exception:            # pragma: no cover - defensive
            return None

    # ---------------- AOT (bench integration) ----------------
    def aot_compile(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower + compile the step for this batch's shape bucket ahead
        of time and pin the executable, so the timed loop never pays a
        second jit compile; returns XLA's flop count for the ONE program
        the chip runs per step (or None where cost_analysis is
        unavailable). Does not advance optimizer counts."""
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode != "fused" or self._host_allreduce() \
                or self._zero_ok is not None:
            # zero mode: jit-compiles on first step; AOT flop pinning is
            # not wired for the sharded signature yet
            return None
        entry, traced = self._entry_for(args, kwargs)
        if entry["exe"] is not None:
            return entry["flops"]
        if batch_size is None:
            batch_size = _infer_batch_size(traced)
        states = self._ensure_states()
        pds = tuple(p._data._data for p in self._all_params)
        sts = tuple(tuple(s._data for s in st) for st in states)
        leaf_datas = tuple(l._data if isinstance(l, NDArray) else l
                           for l in traced)
        n = len(self._trainer._params)
        lrs = onp.zeros(n, onp.float32)
        wds = onp.zeros(n, onp.float32)
        ts = onp.ones(n, onp.int32)
        rescale = onp.float32(1.0 / batch_size)
        clip = onp.float32(0.0)
        key = next_key()
        try:
            exe = entry["fn"].lower(pds, sts, leaf_datas, lrs, wds, ts,
                                    rescale, clip, key).compile()
        except Exception as e:   # pragma: no cover - platform-dependent
            _LOG.warning("compile_step: AOT lower/compile unavailable "
                         "(%s); falling back to jit", type(e).__name__)
            return None
        entry["exe"] = exe
        try:
            ca = exe.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            f = float(ca.get("flops", 0.0))
            entry["flops"] = f if f > 0 else None
        except Exception:        # pragma: no cover - platform-dependent
            entry["flops"] = None
        return entry["flops"]

    # ---------------- telemetry (mx.telemetry MFU gauge) ----------------
    def step_flops(self, *args, batch_size: Optional[int] = None,
                   **kwargs):
        """FLOPs of THIS batch bucket's compiled program, from XLA's
        ``cost_analysis()`` — the numerator of the live MFU gauge
        (docs/OBSERVABILITY.md). Reuses the AOT executable's count when
        :meth:`aot_compile` ran; otherwise lowers+compiles the bucket
        once via the cached :meth:`lower_entry` analysis artifact and
        caches the count. Returns None on the eager path (no program)
        or where cost_analysis is unavailable. For the split (dist
        store) mode the count covers the grad program only — the update
        program's FLOPs are negligible next to fwd+bwd."""
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode != "fused":
            return None
        entry, _ = self._entry_for(args, kwargs)
        if entry.get("flops") is not None:
            return entry["flops"]
        if "flops_cost" in entry:
            return entry["flops_cost"]
        flops = None
        try:
            info = self.lower_entry(*args, batch_size=batch_size,
                                    **kwargs)
            if info is not None:
                ca = info["lowered"].compile().cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                f = float(ca.get("flops", 0.0))
                flops = f if f > 0 else None
        except Exception as e:   # pragma: no cover - platform-dependent
            _LOG.warning("step_flops: cost_analysis unavailable "
                         "(%s: %s)", type(e).__name__, e)
        entry["flops_cost"] = flops
        return flops


class TrainLoop:
    """Convenience wrapper for the canonical (net, loss, trainer) triple:

        loop = gluon.TrainLoop(net, trainer, loss_block)
        for x, y in batches:
            loss = loop.step(x, y)     # ONE compiled XLA program

    ``step(*inputs, label)`` feeds all but the last array to ``net`` and
    the last to the loss block, through ``Trainer.compile_step`` — the
    framework-level replacement for hand-rolled jitted train steps.

    **Async dispatch** (docs/PERF_NOTES.md "async engine"): ``step()``
    returns IMMEDIATELY with an async loss NDArray — JAX arrays are
    futures, and the loop never forces them. A bounded in-flight window
    (``mx.engine.DispatchWindow``, size ``MXNET_INFLIGHT_STEPS`` /
    ``inflight=``, default 2; ``NaiveEngine`` forces 0) reproduces the
    reference engine's ``PushAsync``/``WaitForVar`` discipline: the host
    dispatches ahead of the device and blocks only when the window
    fills, on the OLDEST step's loss. A step that faulted raises at its
    own retire — named by step number — not at a later sync with the
    wrong traceback. ``synchronize()`` drains the window;
    ``engine_stats()`` reports pushes/retires/max-pending plus the last
    prefetcher's input-wait stats. The whole ``step()`` body is a
    transfer-guard hot region: with ``MXNET_TRANSFER_GUARD=raise`` any
    host sync OTHER than the blessed window retire (and checkpoint
    snapshots) raises.

    **Device input prefetch**: ``for x, y in loop.prefetch(batches):``
    stages upcoming host batches onto the device with the step's exact
    sharding on a background thread, overlapping the host→device copy
    with the previous step's compute (gluon/data/prefetcher.py).

    **Numerics observability** (``numerics=`` / ``MXNET_NUMERICS``,
    docs/OBSERVABILITY.md "numerics"): the compiled step's in-program
    grad/param health statistics (global grad norm, update/weight
    ratio, non-finite counts, per-layer norms) ride the dispatch
    window alongside each loss and surface as ``mx_numerics_*`` series
    plus divergence anomalies at the blessed retire — zero extra host
    syncs; a non-finite gradient triggers one NaN-origin forensic
    re-execution and an atomic post-mortem dump.

    **Preemption safety** (``checkpoint_dir=...``): the loop owns a
    ``mx.checkpoint.TrainCheckpointManager`` — on construction it
    auto-resumes from the newest VALID checkpoint (params, fused/ZeRO
    optimizer state, update counters, RNG; corrupt ones are skipped
    with a warning), every ``checkpoint_every`` steps it snapshots
    device state synchronously and commits the write atomically on a
    background thread (serialization overlaps the next steps), and it
    keeps the newest ``keep_last`` checkpoints. A run killed at ANY
    instant — including mid-commit — restarts from the last published
    checkpoint and replays forward bit-exactly (docs/ROBUSTNESS.md).
    A failed background write surfaces on the next ``step()``/``wait()``.
    """

    def __init__(self, net, trainer, loss, donate: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 keep_last: int = 3, async_checkpoint: bool = True,
                 resume: bool = True, inflight: Optional[int] = None,
                 numerics: Optional[str] = None):
        from .. import engine as _engine
        self._net = net
        self._loss = loss
        self._trainer = trainer
        self._step = trainer.compile_step(self._loss_fn, donate=donate,
                                          numerics=numerics)
        self._window = _engine.DispatchWindow(max_inflight=inflight,
                                              what="TrainLoop step")
        self._prefetcher = None
        self._global_step = 0
        t = _telemetry()
        self._m_steps = t.registry().counter(t.names.TRAIN_STEPS)
        self._every = checkpoint_every
        self._manager = None
        if checkpoint_dir is not None:
            from ..checkpoint.manager import TrainCheckpointManager
            self._manager = TrainCheckpointManager(
                checkpoint_dir, keep_last=keep_last,
                async_save=async_checkpoint)
            if resume:
                meta = self._manager.restore_latest(
                    trainer=trainer, net=net, strict=False)
                if meta is not None:
                    self._global_step = int(meta.get("step", 0))
                    _LOG.info("TrainLoop resumed at step %d from %s",
                              self._global_step, checkpoint_dir)

    def _loss_fn(self, *batch):
        *inputs, label = batch
        out = self._net(*inputs)
        return self._loss(out, label)

    def step(self, *batch, batch_size: Optional[int] = None):
        try:
            return self._step_impl(batch, batch_size)
        except (KeyboardInterrupt, SystemExit) as intr:
            # an interrupt mid-hot-loop used to abandon the dispatch
            # window (in-flight steps and their deferred errors silently
            # dropped) — drain it, surface the earliest faulted step's
            # error, and leave a final checkpoint behind
            fault = self._interrupt_cleanup()
            if fault is not None:
                raise fault from intr
            raise

    def _step_impl(self, batch, batch_size):
        # the WHOLE pipelined iteration is a transfer-guard hot region
        # (nested inside CompiledTrainStep's own scope this is a no-op):
        # the window retire below and the checkpoint snapshot are the
        # only blessed syncs; anything else — a float(loss) leaking in,
        # a per-step metric asnumpy — is flagged/raised when
        # MXNET_TRANSFER_GUARD is armed
        with _tguard.hot_scope("TrainLoop.step"):
            t = _telemetry()
            step_no = self._global_step + 1
            if t.active():
                # dispatch span + the XProf bridge: StepTraceAnnotation
                # groups this step's device kernels under the same step
                # number the host spans carry, so the merged trace
                # aligns host phases with XLA execution
                t0 = time.perf_counter()
                with jax.profiler.StepTraceAnnotation(
                        "mx_train_step", step_num=step_no):
                    loss = self._step(*batch, batch_size=batch_size)
                t.timeline().record("dispatch", t0,
                                    time.perf_counter(), step=step_no)
            else:
                loss = self._step(*batch, batch_size=batch_size)
            self._global_step = step_no
            self._m_steps.inc()
            d = loss._data if isinstance(loss, NDArray) else loss
            # the numerics aux (MXNET_NUMERICS) rides the window with
            # the loss and is read at the blessed retire — sync-free
            self._window.push(d, tag=self._global_step,
                              aux=self._step.take_numerics())
            if self._manager is not None and self._every and \
                    self._global_step % self._every == 0:
                with _tguard.allow_transfers("checkpoint snapshot"):
                    self.save_checkpoint()
        return loss

    __call__ = step

    def _interrupt_cleanup(self):
        """KeyboardInterrupt/SIGTERM landed in the hot loop: drain the
        window (a deferred async failure in it is the REAL story — the
        earliest faulted step's error is returned for the caller to
        propagate instead of the bare interrupt) and, when a checkpoint
        manager is attached, commit a final checkpoint so the
        interrupted run resumes from where it actually stopped."""
        fault = None
        try:
            self._window.drain()
        except BaseException as e:
            fault = e
            try:
                self._window.abandon()
            except Exception:    # pragma: no cover - defensive
                pass
        if self._manager is not None:
            try:
                with _tguard.allow_transfers("interrupt final checkpoint"):
                    self._manager.save(self._global_step,
                                       trainer=self._trainer,
                                       net=self._net, block=True)
            except Exception:
                _LOG.warning("final checkpoint on interrupt failed",
                             exc_info=True)
        return fault

    # ---------------- async engine surface ----------------
    def synchronize(self):
        """Drain the in-flight dispatch window — ``WaitForVar`` on every
        outstanding step. Deferred async errors surface here attributed
        to the step that faulted."""
        self._window.drain()

    def discard_inflight(self):
        """Recovery-path window cleanup (``mx.elastic``): retire the
        in-flight steps that still complete, then discard everything
        after the first failure — their results died with the device;
        the newest checkpoint is the source of truth. Returns
        ``(retired, discarded_tags)``."""
        return self._window.drain_partial()

    def prefetch(self, batches, depth: Optional[int] = None):
        """Wrap a host batch iterable in a device prefetcher staged with
        THIS loop's input sharding (dp-sharded batch on a mesh,
        replicated otherwise)::

            for x, y in loop.prefetch(loader):
                loop.step(x, y)

        The host→device copy of batch N+1 overlaps step N's compute.
        ``depth`` bounds staged batches (``MXNET_DEVICE_PREFETCH``,
        default 2). Stats land in :meth:`engine_stats`."""
        from .data.prefetcher import DevicePrefetcher
        self._prefetcher = DevicePrefetcher(
            batches, depth=depth, place=self._step.input_placement())
        return self._prefetcher

    def arm_mfu(self, *batch, peak_flops: Optional[float] = None,
                batch_size: Optional[int] = None) -> Optional[float]:
        """Arm the live MFU gauge (``mx_model_mfu_ratio``): read this
        batch bucket's FLOPs from XLA ``cost_analysis()``
        (:meth:`CompiledTrainStep.step_flops`) into the telemetry
        watchdog; ``peak_flops`` (FLOP/s — bench's measured roofline or
        the chip's spec peak) arms the denominator. The watchdog then
        updates flops/s and MFU on every window retire. Call OUTSIDE
        the timed loop: the first call per bucket may pay one
        lower+compile. Returns the per-step FLOPs (None where no
        compiled program / cost model exists)."""
        flops = self._step.step_flops(*batch, batch_size=batch_size)
        wd = _telemetry().watchdog()
        if flops:
            wd.set_model_flops(flops)
        if peak_flops:
            wd.set_peak_flops(peak_flops)
        return flops

    def engine_stats(self) -> dict:
        """Dispatch/prefetch observability: the in-flight window size and
        its push/retire counters, plus the last :meth:`prefetch`
        iterator's input-wait numbers (tools/diagnose.py --engine)."""
        s = dict(self._window.stats)
        s["inflight_window"] = self._window.max_inflight
        s["pending"] = len(self._window)
        if self._prefetcher is not None:
            s.update(self._prefetcher.stats)
        return s

    # ---------------- checkpointing ----------------
    def save_checkpoint(self, block: Optional[bool] = None):
        """Snapshot now (at ``global_step``); async unless
        ``block=True``. No-op without ``checkpoint_dir``."""
        if self._manager is None:
            raise MXNetError(
                "TrainLoop was built without checkpoint_dir=")
        self._manager.save(self._global_step, trainer=self._trainer,
                           net=self._net, block=block)

    def wait(self):
        """Drain the in-flight checkpoint write (re-raising its error);
        call before exiting so the newest snapshot is durable."""
        if self._manager is not None:
            self._manager.wait()

    @property
    def global_step(self) -> int:
        return self._global_step

    @property
    def checkpoint_manager(self):
        return self._manager

    @property
    def compiled_step(self) -> CompiledTrainStep:
        return self._step

    @property
    def trainer(self):
        return self._trainer
