"""Fused whole-train-step compilation (``Trainer.compile_step``).

The reference MXNet fuses the UPDATE side of training (multi-tensor
``multi_sgd_*`` kernels, ``update_on_kvstore``) but still pays an
imperative dispatch per op and a host boundary between backward and the
optimizer. Here the canonical Gluon loop

    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)

compiles into ONE donated-buffer XLA program per input-shape bucket:
forward (via the same functional binding the CachedOp uses —
``block.ParamBinding``), ``jax.value_and_grad`` of the summed loss over
the parameter pytree (the seed-ones equivalent of ``loss.backward()``),
gradient rescale/clip, the data-parallel reduction (a no-op/psum XLA
inserts for single-process stores; host ``pushpull_list`` between two
programs for dist stores), and the optimizer's ``_rule`` — the idiom the
fusion literature shows dominates TPU efficiency (arXiv:2301.13062) and
that enables in-graph weight-update optimization (arXiv:2004.13336).

Contracts:

- **Traced hyperparameters.** lr/wd/update-count/rescale_grad (and the
  clip bound) enter the program as traced arguments packed in small host
  arrays — ``trainer.learning_rate = x``, a scheduler tick, or a new
  ``step(batch_size)`` NEVER retrace. One compile per input-shape bucket
  (LRU-capped by ``MXNET_FUSED_STEP_CACHE_SIZE``, like the CachedOp's
  ``_jit_lru``).
- **Donation.** Weight and optimizer-state buffers are donated
  (``donate_argnums``) so XLA updates them in place in HBM; after each
  call the results are written back INTO the same ``Parameter._data``
  and state NDArray handles (``Parameter._write_fused``), so handles
  users hold from ``param.data()`` stay valid. Raw ``jax.Array`` objects
  captured from ``param.data()._data`` before a step are invalidated by
  donation — snapshot via ``asnumpy()``/``copy`` instead.
- **Transparent fallback.** Sparse-grad or multi-precision parameters,
  ``update_on_kvstore`` stores, and blocks whose forward cannot trace
  (host-side numpy, data-dependent Python control flow) fall back to the
  eager record/backward/step loop with identical numerics.
"""
from __future__ import annotations

import logging
import os
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from .. import _tape
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray.random import next_key, push_trace_key, pop_trace_key
from .block import ParamBinding, _TRACED

__all__ = ["CompiledTrainStep", "TrainLoop"]

_LOG = logging.getLogger("mxnet_tpu.fused_step")

_ARRAY_TYPES = (NDArray, onp.ndarray, jax.Array)


def _infer_batch_size(traced) -> int:
    for leaf in traced:
        d = leaf._data if isinstance(leaf, NDArray) else leaf
        if getattr(d, "ndim", 0) >= 1:
            return int(d.shape[0])
    return 1


class CompiledTrainStep:
    """One callable = one full training step, compiled.

    Built by ``Trainer.compile_step(loss_fn)``. ``loss_fn(*batch)`` is
    ordinary imperative Gluon code returning a loss NDArray; calling the
    step runs forward+backward+allreduce+update and returns the loss.
    Gradient semantics match ``loss.backward()`` (seed ones — the summed
    loss is differentiated) followed by ``trainer.step(batch_size)``.
    """

    def __init__(self, trainer, loss_fn: Callable, donate: bool = True,
                 train_mode: bool = True):
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._donate = donate
        self._train = train_mode
        self._mode: Optional[str] = None   # None→undecided, 'fused'|'eager'
        self._lru: "OrderedDict[Any, dict]" = OrderedDict()
        self._trace_signatures: set = set()
        self._n_traces = 0
        self._steps_done = 0

        # dedup while preserving order: tied params may appear twice in a
        # collected dict; bind each object once
        seen: set = set()
        self._all_params = []
        for p in trainer._all_params:
            if id(p) not in seen:
                seen.add(id(p))
                self._all_params.append(p)
        pos = {id(p): i for i, p in enumerate(self._all_params)}
        # trainer._params (grad_req != null) carry the optimizer indices
        self._trainable_pos = [pos[id(p)] for p in trainer._params]

    # ---------------- introspection ----------------
    @property
    def n_traces(self) -> int:
        """Distinct compiled step programs built so far (the retrace
        counter tests assert on — trace-time side effect, stable under
        jit-cache eviction)."""
        return self._n_traces

    @property
    def mode(self) -> Optional[str]:
        return self._mode

    # ---------------- mode decision ----------------
    def _decide_mode(self) -> str:
        tr = self._trainer
        if not tr._kv_initialized:
            # single-process in-program stores need no kvstore at all —
            # seeding one would alias param buffers that donation later
            # invalidates. Dist stores DO need init (for pushpull_list).
            kind = tr._kvstore_kind
            needs_kv = kind is not None and (
                not isinstance(kind, str) or "dist" in kind)
            if needs_kv:
                tr._init_kvstore()
            else:
                tr._update_on_kvstore = False
        if tr._update_on_kvstore:
            return "eager"   # optimizer lives on the store: cannot fuse
        for p in self._all_params:
            if p._data is None:
                return "eager"   # deferred shapes: eager forward infers
            if p.stype != "default" or p._grad_stype != "default":
                return "eager"   # sparse storage/grad: lazy row path
        opt = self._trainer._optimizer
        if opt.multi_precision and any(
                p._data._data.dtype in (jnp.float16, jnp.bfloat16)
                for p in self._trainer._params):
            return "eager"       # master-weight states: not fused yet
        return "fused"

    def _host_allreduce(self) -> bool:
        kv = self._trainer._kvstore
        # unknown custom stores default to the conservative host path
        return kv is not None and not getattr(kv, "in_program_reduce",
                                              False)

    # ---------------- call ----------------
    def __call__(self, *args, batch_size: Optional[int] = None, **kwargs):
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode == "eager":
            return self._eager_call(args, kwargs, batch_size)
        opt = self._trainer._optimizer
        # first call: the trace may fail AFTER hyperparameter counts were
        # advanced — snapshot so the eager fallback replays step 1 as
        # step 1 (Adam's bias correction depends on t)
        snapshot = (opt.num_update, dict(opt._index_update_count)) \
            if not self._steps_done else None
        try:
            out = self._fused_call(args, kwargs, batch_size)
        except Exception as e:
            if self._steps_done:
                raise   # the program is proven; this is a genuine error
            _LOG.warning(
                "compile_step: fused trace failed (%s: %s); falling back "
                "to the eager tape path", type(e).__name__, e)
            opt.num_update, opt._index_update_count = \
                snapshot[0], snapshot[1]
            self._mode = "eager"
            return self._eager_call(args, kwargs, batch_size)
        self._steps_done += 1
        return out

    step = __call__

    # ---------------- eager fallback ----------------
    def _eager_call(self, args, kwargs, batch_size):
        from .. import autograd
        wrap = lambda a: a if isinstance(a, NDArray) or not isinstance(
            a, (onp.ndarray, jax.Array)) else NDArray(a)   # noqa: E731
        args = tuple(wrap(a) for a in args)
        kwargs = {k: wrap(v) for k, v in kwargs.items()}
        with autograd.record(train_mode=self._train):
            loss = self._loss_fn(*args, **kwargs)
        _tape.backward([loss])
        if batch_size is None:
            batch_size = _infer_batch_size(
                [a for a in args if isinstance(a, NDArray)])
        self._trainer.step(batch_size)
        self._steps_done += 1
        return loss

    # ---------------- fused path ----------------
    def _flatten(self, args, kwargs):
        all_leaves, arg_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda t: isinstance(t, NDArray))
        traced = [l for l in all_leaves if isinstance(l, _ARRAY_TYPES)]
        static_spec = tuple(_TRACED if isinstance(l, _ARRAY_TYPES) else l
                            for l in all_leaves)
        nd_mask = tuple(isinstance(l, NDArray) for l in traced)
        return traced, arg_treedef, static_spec, nd_mask

    @staticmethod
    def _cache_cap() -> int:
        try:
            return int(os.environ.get("MXNET_FUSED_STEP_CACHE_SIZE", "0"))
        except ValueError:
            return 0

    def _entry_for(self, args, kwargs):
        traced, arg_treedef, static_spec, nd_mask = self._flatten(
            args, kwargs)
        shapes = tuple(
            (tuple((l._data if isinstance(l, NDArray) else l).shape),
             str((l._data if isinstance(l, NDArray) else l).dtype))
            for l in traced)
        sig = (self._train, arg_treedef, static_spec, nd_mask, shapes)
        entry = self._lru.get(sig)
        if entry is None:
            entry = self._build_bucket(arg_treedef, static_spec, nd_mask)
            self._lru[sig] = entry
            self._trace_signatures.add(sig)
            cap = self._cache_cap()
            while cap > 0 and len(self._lru) > cap:
                self._lru.popitem(last=False)
        else:
            self._lru.move_to_end(sig)
        return entry, traced

    def _build_bucket(self, arg_treedef, static_spec, nd_mask) -> dict:
        params = self._all_params
        loss_fn = self._loss_fn
        train = self._train
        t_pos = tuple(self._trainable_pos)
        opt_fn = self._trainer._optimizer.fused_step_fn()
        donate = (0, 1) if self._donate else ()
        step_self = self

        def run_loss(pds, traced_leaves, key):
            it = iter(NDArray(l) if m else l
                      for l, m in zip(traced_leaves, nd_mask))
            leaves = [next(it) if s is _TRACED else s for s in static_spec]
            args, kwargs = jax.tree_util.tree_unflatten(arg_treedef, leaves)
            binding = ParamBinding(params, pds)
            push_trace_key(key)
            prev_r = _tape.set_recording(False)
            prev_s = _tape.set_taping_suspended(True)
            prev_t = _tape.set_training(train)
            try:
                with binding:
                    out = loss_fn(*args, **kwargs)
            finally:
                _tape.set_recording(prev_r)
                _tape.set_taping_suspended(prev_s)
                _tape.set_training(prev_t)
                pop_trace_key()
            l = out._data if isinstance(out, NDArray) else jnp.asarray(out)
            # differentiate the SUM: identical to loss.backward() seeding
            # ones over the per-sample loss vector
            return jnp.sum(l), (l, binding.state)

        def grad_part(pds, traced_leaves, key):
            (_, (l, state)), grads = jax.value_and_grad(
                run_loss, has_aux=True)(tuple(pds), traced_leaves, key)
            gs = tuple(grads[i] for i in t_pos)
            return l, state, gs

        if self._host_allreduce():
            # split mode (dist stores): program A computes loss+grads+
            # functional state; the kvstore's bucketed pushpull_list runs
            # between programs; program B is the donated fused update.
            grad_fn = jax.jit(grad_part)

            def update(ws, sts, lrs, wds, ts, rescale, clip, gs):
                step_self._n_traces += 1
                return opt_fn(ws, gs, lrs, wds, ts, rescale, clip, sts)

            return {"kind": "split", "grad": grad_fn,
                    "update": jax.jit(update, donate_argnums=donate),
                    "exe": None, "flops": None}

        def fused(pds, sts, traced_leaves, lrs, wds, ts, rescale, clip,
                  key):
            step_self._n_traces += 1
            l, state, gs = grad_part(pds, traced_leaves, key)
            ws = tuple(pds[i] for i in t_pos)
            new_ws, new_sts = opt_fn(ws, gs, lrs, wds, ts, rescale, clip,
                                     sts)
            new_pds = list(state)   # BN-stat rebinds + identity for rest
            for j, i in enumerate(t_pos):
                new_pds[i] = new_ws[j]
            return tuple(new_pds), new_sts, l

        return {"kind": "fused",
                "fn": jax.jit(fused, donate_argnums=donate),
                "exe": None, "flops": None}

    def _ensure_states(self):
        updater = self._trainer._updater
        for i, p in enumerate(self._trainer._params):
            if i not in updater.states:
                updater.states[i] = \
                    self._trainer._optimizer.create_state_multi_precision(
                        i, p.data())
        return [updater.states[i]
                for i in range(len(self._trainer._params))]

    def _scalars(self, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        opt.rescale_grad = tr._scale / batch_size
        lrs, wds, ts = opt.begin_fused_step(
            list(range(len(tr._params))))
        rescale = onp.float32(opt.rescale_grad)
        clip = onp.float32(opt.clip_gradient
                           if opt.clip_gradient is not None else 0.0)
        return lrs, wds, ts, rescale, clip

    def _fused_call(self, args, kwargs, batch_size):
        entry, traced = self._entry_for(args, kwargs)
        if batch_size is None:
            batch_size = _infer_batch_size(traced)
        states = self._ensure_states()
        for st in states:
            if not (isinstance(st, tuple) and all(
                    isinstance(s, NDArray) for s in st)):
                raise MXNetError(
                    "compile_step: optimizer state is not a flat NDArray "
                    "tuple (multi-precision?); eager path required")
        pds = tuple(p._data._data for p in self._all_params)
        sts = tuple(tuple(s._data for s in st) for st in states)
        leaf_datas = tuple(l._data if isinstance(l, NDArray) else l
                           for l in traced)
        lrs, wds, ts, rescale, clip = self._scalars(batch_size)
        key = next_key()

        if entry["kind"] == "split":
            l, state, gs = entry["grad"](pds, leaf_datas, key)
            # land gradients on the Parameter grad handles and reuse the
            # Trainer's own reduction machinery (bucketed pushpull_list)
            tr = self._trainer
            for p, g in zip(tr._params, gs):
                p.grad()._data = g
            tr._allreduce_grads()
            gs = tuple(p.grad()._data for p in tr._params)
            ws = tuple(pds[i] for i in self._trainable_pos)
            new_ws, new_sts = entry["update"](ws, sts, lrs, wds, ts,
                                              rescale, clip, gs)
            new_pds = list(state)
            for j, i in enumerate(self._trainable_pos):
                new_pds[i] = new_ws[j]
        else:
            fn = entry["exe"] or entry["fn"]
            new_pds, new_sts, l = fn(pds, sts, leaf_datas, lrs, wds, ts,
                                     rescale, clip, key)

        # writeback: same handles, new buffers (donation contract)
        for p, nw in zip(self._all_params, new_pds):
            p._write_fused(nw)
        for st, ns in zip(states, new_sts):
            for s, n in zip(st, ns):
                s._data = n
        return NDArray(l)

    # ---------------- AOT (bench integration) ----------------
    def aot_compile(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower + compile the step for this batch's shape bucket ahead
        of time and pin the executable, so the timed loop never pays a
        second jit compile; returns XLA's flop count for the ONE program
        the chip runs per step (or None where cost_analysis is
        unavailable). Does not advance optimizer counts."""
        if self._mode is None:
            self._mode = self._decide_mode()
        if self._mode != "fused" or self._host_allreduce():
            return None
        entry, traced = self._entry_for(args, kwargs)
        if entry["exe"] is not None:
            return entry["flops"]
        if batch_size is None:
            batch_size = _infer_batch_size(traced)
        states = self._ensure_states()
        pds = tuple(p._data._data for p in self._all_params)
        sts = tuple(tuple(s._data for s in st) for st in states)
        leaf_datas = tuple(l._data if isinstance(l, NDArray) else l
                           for l in traced)
        n = len(self._trainer._params)
        lrs = onp.zeros(n, onp.float32)
        wds = onp.zeros(n, onp.float32)
        ts = onp.ones(n, onp.int32)
        rescale = onp.float32(1.0 / batch_size)
        clip = onp.float32(0.0)
        key = next_key()
        try:
            exe = entry["fn"].lower(pds, sts, leaf_datas, lrs, wds, ts,
                                    rescale, clip, key).compile()
        except Exception as e:   # pragma: no cover - platform-dependent
            _LOG.warning("compile_step: AOT lower/compile unavailable "
                         "(%s); falling back to jit", type(e).__name__)
            return None
        entry["exe"] = exe
        try:
            ca = exe.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            f = float(ca.get("flops", 0.0))
            entry["flops"] = f if f > 0 else None
        except Exception:        # pragma: no cover - platform-dependent
            entry["flops"] = None
        return entry["flops"]


class TrainLoop:
    """Convenience wrapper for the canonical (net, loss, trainer) triple:

        loop = gluon.TrainLoop(net, trainer, loss_block)
        for x, y in batches:
            loss = loop.step(x, y)     # ONE compiled XLA program

    ``step(*inputs, label)`` feeds all but the last array to ``net`` and
    the last to the loss block, through ``Trainer.compile_step`` — the
    framework-level replacement for hand-rolled jitted train steps.
    """

    def __init__(self, net, trainer, loss, donate: bool = True):
        self._net = net
        self._loss = loss
        self._trainer = trainer
        self._step = trainer.compile_step(self._loss_fn, donate=donate)

    def _loss_fn(self, *batch):
        *inputs, label = batch
        out = self._net(*inputs)
        return self._loss(out, label)

    def step(self, *batch, batch_size: Optional[int] = None):
        return self._step(*batch, batch_size=batch_size)

    __call__ = step

    @property
    def compiled_step(self) -> CompiledTrainStep:
        return self._step

    @property
    def trainer(self):
        return self._trainer
