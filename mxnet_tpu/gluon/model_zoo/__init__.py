"""Model zoo (reference: python/mxnet/gluon/model_zoo/__init__.py).

Pretrained-weight downloads are not available in this environment; models are
constructed with random init and support ``load_parameters`` from local files.
"""
from . import vision
from . import bert
from .vision import get_model

__all__ = ["vision", "bert", "get_model"]
