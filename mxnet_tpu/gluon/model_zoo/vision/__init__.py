"""Vision model zoo (reference: python/mxnet/gluon/model_zoo/vision/__init__.py).

``get_model(name, **kwargs)`` constructs any model by its reference name.
"""
from ....base import MXNetError
from .alexnet import *
from .densenet import *
from .inception import *
from .mobilenet import *
from .resnet import *
from .squeezenet import *
from .vgg import *

from . import alexnet as _alexnet
from . import densenet as _densenet
from . import inception as _inception
from . import mobilenet as _mobilenet
from . import resnet as _resnet
from . import squeezenet as _squeezenet
from . import vgg as _vgg

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "alexnet": alexnet,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "inceptionv3": inception_v3,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "mobilenetv3_large": mobilenet_v3_large,
    "mobilenetv3_small": mobilenet_v3_small,
}


def get_model(name, **kwargs):
    """Construct a model by name (reference vision/__init__.py get_model)."""
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name} is not in the zoo; available: {sorted(_models)}")
    return _models[name](**kwargs)
