"""MobileNet V1/V2/V3 (reference: python/mxnet/gluon/model_zoo/vision/mobilenet.py
plus the V3 variant the reference ships in gluon-cv form).

Depthwise convs map to ``feature_group_count=channels`` grouped
lax.conv_general_dilated, which XLA lowers efficiently on TPU.
"""
from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "MobileNetV3",
           "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
           "mobilenet_v2_0_25", "mobilenet_v3_large", "mobilenet_v3_small",
           "get_mobilenet", "get_mobilenet_v2"]


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False, act_type="relu"):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        if relu6:
            out.add(nn.Lambda(lambda x: x.clip(0, 6)))
        elif act_type == "hswish":
            out.add(nn.Lambda(lambda x: x * (x + 3).clip(0, 6) / 6))
        else:
            out.add(nn.Activation(act_type))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    """MobileNetV2 inverted residual (expand-depthwise-project)."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        self.out = nn.HybridSequential()
        if t != 1:
            _add_conv(self.out, in_channels * t, relu6=True)
        _add_conv(self.out, in_channels * t, kernel=3, stride=stride, pad=1,
                  num_group=in_channels * t, relu6=True)
        _add_conv(self.out, channels, active=False)

    def forward(self, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    """MobileNetV1 (Howard et al. 1704.04861)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), kernel=3, stride=2,
                  pad=1)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2, 1, 2, 1, 2, 1, 1, 1, 1, 1, 2, 1]
        for dwc, c, s in zip(dw_channels, channels, strides):
            _add_conv_dw(self.features, dwc, c, s)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class MobileNetV2(HybridBlock):
    """MobileNetV2 (Sandler et al. 1801.04381)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), kernel=3, stride=2,
                  pad=1, relu6=True)
        in_channels_group = [int(x * multiplier) for x in
                             [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4 +
                             [96] * 3 + [160] * 3]
        channels_group = [int(x * multiplier) for x in
                          [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3 +
                          [160] * 3 + [320]]
        ts = [1] + [6] * 16
        strides = [1, 2, 1, 2, 1, 1, 2, 1, 1, 1, 1, 1, 1, 2, 1, 1, 1]
        for in_c, c, t, s in zip(in_channels_group, channels_group, ts,
                                 strides):
            self.features.add(LinearBottleneck(in_c, c, t, s))
        last_channels = int(1280 * multiplier) if multiplier > 1.0 else 1280
        _add_conv(self.features, last_channels, relu6=True)
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, use_bias=False))
        self.output.add(nn.Flatten())

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class _SE(HybridBlock):
    """Squeeze-and-excitation used by MobileNetV3."""

    def __init__(self, channels, reduction=4, **kwargs):
        super().__init__(**kwargs)
        self.fc1 = nn.Conv2D(channels // reduction, 1, use_bias=True)
        self.fc2 = nn.Conv2D(channels, 1, use_bias=True)

    def forward(self, x):
        from ....ndarray import nn_ops as FNN
        w = FNN.Pooling(x, pool_type="avg", global_pool=True)
        w = self.fc1(w).relu()
        w = self.fc2(w)
        w = (w + 3).clip(0, 6) / 6  # hard-sigmoid
        return x * w


class _V3Bottleneck(HybridBlock):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, se, act,
                 **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_c == out_c
        self.body = nn.HybridSequential()
        if exp_c != in_c:
            _add_conv(self.body, exp_c, act_type=act)
        _add_conv(self.body, exp_c, kernel=kernel, stride=stride,
                  pad=kernel // 2, num_group=exp_c, act_type=act)
        if se:
            self.body.add(_SE(exp_c))
        _add_conv(self.body, out_c, active=False)

    def forward(self, x):
        out = self.body(x)
        if self.use_shortcut:
            out = out + x
        return out


# (kernel, exp, out, SE, activation, stride)
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2), (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1), (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1), (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2), (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
]
_V3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1), (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1), (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2), (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
]


class MobileNetV3(HybridBlock):
    def __init__(self, mode="large", classes=1000, multiplier=1.0, **kwargs):
        super().__init__(**kwargs)
        spec = _V3_LARGE if mode == "large" else _V3_SMALL
        last_exp = 960 if mode == "large" else 576
        last_ch = 1280 if mode == "large" else 1024
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(16 * multiplier), kernel=3, stride=2,
                  pad=1, act_type="hswish")
        in_c = int(16 * multiplier)
        for k, exp, out_c, se, act, s in spec:
            exp_c = int(exp * multiplier)
            o = int(out_c * multiplier)
            self.features.add(_V3Bottleneck(in_c, exp_c, o, k, s, se, act))
            in_c = o
        _add_conv(self.features, int(last_exp * multiplier),
                  act_type="hswish")
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(last_ch, 1, use_bias=True))
        self.output.add(nn.Lambda(lambda x: x * (x + 3).clip(0, 6) / 6))
        self.output.add(nn.Conv2D(classes, 1, use_bias=True))
        self.output.add(nn.Flatten())

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None, **kwargs):
    return MobileNet(multiplier, **kwargs)


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    return MobileNetV2(multiplier, **kwargs)


def mobilenet1_0(**kwargs):
    return get_mobilenet(1.0, **kwargs)


def mobilenet0_75(**kwargs):
    return get_mobilenet(0.75, **kwargs)


def mobilenet0_5(**kwargs):
    return get_mobilenet(0.5, **kwargs)


def mobilenet0_25(**kwargs):
    return get_mobilenet(0.25, **kwargs)


def mobilenet_v2_1_0(**kwargs):
    return get_mobilenet_v2(1.0, **kwargs)


def mobilenet_v2_0_75(**kwargs):
    return get_mobilenet_v2(0.75, **kwargs)


def mobilenet_v2_0_5(**kwargs):
    return get_mobilenet_v2(0.5, **kwargs)


def mobilenet_v2_0_25(**kwargs):
    return get_mobilenet_v2(0.25, **kwargs)


def mobilenet_v3_large(**kwargs):
    return MobileNetV3("large", **kwargs)


def mobilenet_v3_small(**kwargs):
    return MobileNetV3("small", **kwargs)
