"""SqueezeNet 1.0/1.1 (reference: python/mxnet/gluon/model_zoo/vision/squeezenet.py)."""
from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(squeeze_channels, kernel_size=1, activation="relu"))
    out.add(nn.HybridConcatenate(axis=1))
    out[-1].add(nn.Conv2D(expand1x1_channels, kernel_size=1,
                          activation="relu"))
    out[-1].add(nn.Conv2D(expand3x3_channels, kernel_size=3, padding=1,
                          activation="relu"))
    return out


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in ("1.0", "1.1"):
            raise MXNetError("squeezenet version must be '1.0' or '1.1'")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, kernel_size=7, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, kernel_size=3, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(_make_fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, kernel_size=1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def squeezenet1_0(**kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return SqueezeNet("1.1", **kwargs)
