"""BERT model family (flagship language model; BASELINE.md north star
"BERT-base tokens/sec/chip").

Reference parity note: the reference keeps BERT in gluon-nlp (out of tree);
its in-tree model zoo is vision-only (python/mxnet/gluon/model_zoo/). The
TPU build promotes BERT in-tree because the attention stack (Pallas flash
attention, ring attention) is a core framework feature here, not an add-on.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray import ops as F
from ...ndarray.ndarray import arange
from ...ops.registry import invoke_raw
from ..block import HybridBlock
from ..nn.basic_layers import Dense, Dropout, Embedding, LayerNorm
from ..nn.transformer import TransformerEncoder

__all__ = ["BERTModel", "BERTClassifier", "bert_base", "bert_large",
           "bert_small_test"]


class BERTModel(HybridBlock):
    """BERT encoder: token+position+segment embeddings → transformer stack
    → (sequence output, pooled [CLS] output [, masked-LM scores])."""

    def __init__(self, vocab_size: int = 30522, units: int = 768,
                 hidden_size: int = 3072, num_layers: int = 12,
                 num_heads: int = 12, max_length: int = 512,
                 token_type_vocab_size: int = 2, dropout: float = 0.1,
                 use_pooler: bool = True, use_decoder: bool = False,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.word_embed = Embedding(vocab_size, units)
        self.token_type_embed = Embedding(token_type_vocab_size, units)
        self.position_embed = Embedding(max_length, units)
        self.embed_ln = LayerNorm(in_channels=units)
        self.embed_dropout = Dropout(dropout)
        # gelu_tanh: the tanh-polynomial GELU of the original BERT code
        # (google-research/bert modeling.py gelu). Also the faster form on
        # TPU: its backward reuses the forward tanh (1 - t^2) where exact
        # erf-GELU's backward needs a fresh exp(-x^2/2) — measured 12
        # ms/step on bs=32x512 BERT-base (docs/PERF_NOTES.md r5).
        self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                          num_heads, dropout=dropout,
                                          activation="gelu_tanh")
        self.pooler = Dense(units, activation="tanh", flatten=False,
                            in_units=units) if use_pooler else None
        if use_decoder:
            self.decoder_transform = Dense(units, flatten=False,
                                           in_units=units)
            self.decoder_ln = LayerNorm(in_channels=units)
            # output projection ties to word_embed.weight at forward time
        else:
            self.decoder_transform = None

    def forward(self, inputs, token_types=None, valid_length=None):
        b, s = inputs.shape
        if s > self._max_length:
            raise MXNetError(
                f"sequence length {s} exceeds max_length {self._max_length}")
        pos = arange(0, s, dtype="int32")
        x = self.word_embed(inputs)
        x = x + F.broadcast_like(
            F.reshape(self.position_embed(pos), (1, s, self._units)), x)
        if token_types is None:
            token_types = F.zeros_like(inputs)
        x = x + self.token_type_embed(token_types)
        x = self.embed_dropout(self.embed_ln(x))
        # valid_length rides the fused flash path (blockwise key-padding
        # mask) — no S×S additive mask is ever materialized.
        seq = self.encoder(x, valid_length=valid_length)
        outs = [seq]
        if self.pooler is not None:
            cls = F.reshape(F.slice_axis(seq, axis=1, begin=0, end=1),
                            (b, self._units))
            outs.append(self.pooler(cls))
        if self.decoder_transform is not None:
            h = self.decoder_ln(F.Activation(self.decoder_transform(seq),
                                             act_type="gelu"))
            w = self.word_embed.weight.data()
            scores = invoke_raw(
                "bert_decoder_proj",
                lambda hh, ww: jnp.einsum("bsu,vu->bsv", hh, ww), [h, w])
            outs.append(scores)
        return outs[0] if len(outs) == 1 else tuple(outs)


class BERTClassifier(HybridBlock):
    """BERT + dropout + dense head over the pooled output."""

    def __init__(self, bert: BERTModel, num_classes: int = 2,
                 dropout: float = 0.1, **kwargs):
        super().__init__(**kwargs)
        if bert.pooler is None:
            raise MXNetError("BERTClassifier requires a BERTModel built "
                             "with use_pooler=True")
        self.bert = bert
        self.dropout = Dropout(dropout)
        self.classifier = Dense(num_classes, in_units=bert._units)

    def forward(self, inputs, token_types=None, valid_length=None):
        out = self.bert(inputs, token_types, valid_length)
        pooled = out[1]  # (seq, pooled[, mlm_scores]); pooler checked above
        return self.classifier(self.dropout(pooled))


def bert_base(**kwargs):
    """BERT-base: 12 layers, 768 units, 12 heads (110M params)."""
    return BERTModel(units=768, hidden_size=3072, num_layers=12,
                     num_heads=12, **kwargs)


def bert_large(**kwargs):
    """BERT-large: 24 layers, 1024 units, 16 heads (340M params)."""
    return BERTModel(units=1024, hidden_size=4096, num_layers=24,
                     num_heads=16, **kwargs)


def bert_small_test(**kwargs):
    """Tiny config for tests/CI."""
    kwargs.setdefault("vocab_size", 128)
    kwargs.setdefault("max_length", 64)
    return BERTModel(units=32, hidden_size=64, num_layers=2, num_heads=4,
                     **kwargs)
