"""Pretrained-weight store (reference gluon/model_zoo/model_store.py).

Weights resolve in order: an existing local file under ``root`` (default
``$MXNET_HOME/models``), then the repo at ``MXNET_GLUON_REPO`` via
``gluon.utils.download`` — which in this zero-egress build serves ``file://``
mirrors and existing paths only (utils.py download). Point
``MXNET_GLUON_REPO`` at a local mirror (``file:///data/mirror/``) to use
pretrained weights offline.
"""
from __future__ import annotations

import os

from ...base import data_dir
from ..utils import download, _get_repo_url

__all__ = ["get_model_file"]


def get_model_file(name: str, root: str | None = None) -> str:
    """Return a local path to ``<name>.params``, fetching from the repo
    mirror if absent (reference model_store.get_model_file)."""
    root = os.path.expanduser(root or os.path.join(data_dir(), "models"))
    path = os.path.join(root, f"{name}.params")
    if os.path.exists(path):
        return path
    os.makedirs(root, exist_ok=True)
    url = f"{_get_repo_url()}gluon/models/{name}.params"
    return download(url, path=path)
