"""Pretrained-weight store (reference gluon/model_zoo/model_store.py).

Weights resolve in order: an existing local file under ``root`` (default
``$MXNET_HOME/models``) whose sha1 (when known) verifies, then the repo
at ``MXNET_GLUON_REPO`` via ``gluon.utils.download`` — transient fetch
failures retry with backoff, the payload is sha1-verified against
``_model_sha1`` BEFORE being ``os.replace``d into the cache, and a
corrupt transfer is deleted rather than cached. In this zero-egress
build only ``file://`` mirrors and existing paths are served; point
``MXNET_GLUON_REPO`` at a local mirror (``file:///data/mirror/``) to
use pretrained weights offline.
"""
from __future__ import annotations

import logging
import os

from ...base import data_dir, get_env
from ..utils import check_sha1, download, _get_repo_url

__all__ = ["get_model_file", "register_model_sha1"]

_LOG = logging.getLogger("mxnet_tpu.model_zoo")

# name -> sha1 of <name>.params. The reference ships a large literal
# table; here mirrors register theirs (offline mirrors are user-built,
# so the table is an extension point rather than a constant).
_model_sha1 = {}


def register_model_sha1(name: str, sha1: str):
    """Register/override the expected sha1 for ``<name>.params`` so
    cache hits and downloads are integrity-checked."""
    _model_sha1[name] = sha1


def get_model_file(name: str, root: str | None = None,
                   sha1_hash: str | None = None) -> str:
    """Return a local path to ``<name>.params``, fetching from the repo
    mirror if absent (reference model_store.get_model_file). A cached
    file with a known-bad sha1 is re-fetched; the fetch itself is
    retried, verified, and committed atomically."""
    root = os.path.expanduser(root or os.path.join(data_dir(), "models"))
    path = os.path.join(root, f"{name}.params")
    sha1_hash = sha1_hash or _model_sha1.get(name)
    if os.path.exists(path):
        if sha1_hash is None or check_sha1(path, sha1_hash):
            return path
        _LOG.warning("cached %s fails sha1 verification; re-fetching",
                     path)
    os.makedirs(root, exist_ok=True)
    url = f"{_get_repo_url()}gluon/models/{name}.params"
    return download(url, path=path, overwrite=True, sha1_hash=sha1_hash,
                    retries=get_env("MXNET_MODEL_FETCH_RETRIES", 5, int))
