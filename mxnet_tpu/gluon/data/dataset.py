"""Datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os
from typing import Callable, List, Sequence

from ...base import MXNetError
from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset (reference dataset.py:30)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn: Callable) -> "SimpleDataset":
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards: int, index: int) -> "SimpleDataset":
        items = [self[i] for i in range(index, len(self), num_shards)]
        return SimpleDataset(items)

    def take(self, count: int) -> "SimpleDataset":
        return SimpleDataset([self[i]
                              for i in range(min(count, len(self)))])

    def transform(self, fn: Callable, lazy: bool = True) -> "Dataset":
        t = _LazyTransformDataset(self, fn)
        if lazy:
            return t
        return SimpleDataset([t[i] for i in range(len(t))])

    def transform_first(self, fn: Callable, lazy: bool = True) -> "Dataset":
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]
        return self.transform(first, lazy)


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset: Dataset, fn: Callable):
        self._dataset = dataset
        self._fn = fn

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data: Sequence):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays (reference ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference src/io/dataset.cc:63
    RecordFileDataset; our reader is the C++ recordio library when built,
    with a pure-Python fallback — see mxnet_tpu/recordio.py)."""

    def __init__(self, filename: str):
        from ... import recordio
        self._filename = filename
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
