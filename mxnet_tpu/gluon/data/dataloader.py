"""DataLoader: batched, shuffled, prefetching iteration.

Reference analog: python/mxnet/gluon/data/dataloader.py (:513 __iter__;
fork-based _MultiWorkerIter :439 with shared-memory NDArray pickling). TPU
host design: JPEG decode/augment happens on the host CPU while the chip runs
the previous step, so what matters is (a) worker parallelism for decode and
(b) pipelining ahead of the device. We use a thread pool (decode is
numpy/PIL releasing the GIL; fork is hostile to the XLA runtime) plus a
bounded prefetch queue — the analog of the reference's iter_prefetcher.h
double-buffering.
"""
from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as onp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py
    default_batchify_fn; native GIL-free parallel copy when built —
    src/native/batchify.cc)."""
    if isinstance(data[0], NDArray):
        from .batchify import Stack
        return Stack()(data)  # one native-or-numpy stack implementation
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    arr = onp.asarray(data)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    return NDArray(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    """Loads batches from a Dataset (reference DataLoader API: batch_size,
    shuffle, sampler, batch_sampler, last_batch, batchify_fn, num_workers,
    pin_memory, prefetch).

    **Device prefetch** (``device=`` / ``prefetch_to_device=``): when a
    target is given, batches are additionally staged host→device on a
    background thread AHEAD of consumption (gluon/data/prefetcher.py) —
    the copy of batch N+1 overlaps step N's compute instead of
    serializing inside jit dispatch. ``device`` accepts ``True`` (the
    process-default accelerator), an ``mx.Context``, a ``jax.Device``,
    or a ``parallel.DeviceMesh`` (batches land dp-sharded over
    ``device_axis`` when divisible, replicated otherwise — the fused
    train step's exact input layout). ``prefetch_to_device`` bounds the
    staged batches (default ``MXNET_DEVICE_PREFETCH``, 2)."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[Sampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 pin_device_id: int = 0, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120,
                 device=None, prefetch_to_device: Optional[int] = None,
                 device_axis: str = "dp"):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required unless batch_sampler is given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle is mutually exclusive with sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch are mutually "
                "exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(self._num_workers, 1))
        self._timeout = timeout
        self._device = device
        self._device_axis = device_axis
        self._prefetch_to_device = prefetch_to_device
        self._device_prefetcher = None

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._device is not None or self._prefetch_to_device is not None:
            from .prefetcher import DevicePrefetcher
            dev, mesh = self._device, None
            if dev is not None and hasattr(dev, "axis_names"):
                dev, mesh = None, self._device   # a DeviceMesh target
            self._device_prefetcher = DevicePrefetcher(
                self._host_iter(), depth=self._prefetch_to_device,
                device=dev, mesh=mesh, axis=self._device_axis,
                timeout=self._timeout)
            yield from self._device_prefetcher
            return
        yield from self._host_iter()

    @property
    def device_prefetch_stats(self):
        """Staging stats of the most recent device-prefetching iteration
        (``input_wait_ms``, ``starvation_count``, ...), or None."""
        return None if self._device_prefetcher is None \
            else dict(self._device_prefetcher.stats)

    def _host_iter(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        # threaded pipeline with bounded in-flight futures
        # (reference prefetcher double-buffering, src/io/iter_prefetcher.h).
        # Cleanup contract: on a worker exception, a timeout, or the
        # consumer abandoning the iterator (break/close), every remaining
        # in-flight future is cancelled and the pool shut down WITHOUT
        # waiting — a failing dataset must not block behind (or silently
        # run) the rest of the prefetch window.
        from concurrent.futures import TimeoutError as _FutTimeout
        pool = ThreadPoolExecutor(max_workers=self._num_workers)
        inflight = deque()
        try:
            batches = iter(self._batch_sampler)
            for indices in batches:
                inflight.append(pool.submit(self._load_batch, indices))
                if len(inflight) >= self._prefetch:
                    break
            while inflight:
                fut = inflight.popleft()
                try:
                    batch = fut.result(timeout=self._timeout)
                except _FutTimeout:
                    raise MXNetError(
                        f"DataLoader worker produced no batch within "
                        f"timeout={self._timeout}s") from None
                nxt = next(batches, None)
                if nxt is not None:
                    inflight.append(pool.submit(self._load_batch, nxt))
                yield batch
        finally:
            while inflight:
                inflight.popleft().cancel()
            pool.shutdown(wait=False, cancel_futures=True)
