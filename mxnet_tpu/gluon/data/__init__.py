"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from .dataset import (Dataset, SimpleDataset, ArrayDataset,
                      RecordFileDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler,
                      BatchSampler, FilterSampler, IntervalSampler)
from .dataloader import DataLoader, default_batchify_fn
from .prefetcher import DevicePrefetcher
from . import vision
from . import batchify
