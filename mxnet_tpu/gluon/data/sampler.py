"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""
from __future__ import annotations

import numpy as onp

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "FilterSampler", "IntervalSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length: int, start: int = 0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(onp.random.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class IntervalSampler(Sampler):
    def __init__(self, length, interval, rollover=True):
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval) if self._rollover else [0]
        for s in starts:
            yield from range(s, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))


class BatchSampler(Sampler):
    """Wrap a sampler into batches (reference BatchSampler)."""

    def __init__(self, sampler: Sampler, batch_size: int,
                 last_batch: str = "keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    f"last_batch must be keep/discard/rollover, "
                    f"got {self._last_batch}")

    def __len__(self):
        n = len(self._sampler)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + len(self._prev)) // self._batch_size
