"""Vision datasets (reference: gluon/data/vision/datasets.py).

Zero-egress environment: datasets load from local files when present
(standard idx-ubyte / CIFAR binary formats); MNIST/FashionMNIST fall back to
a deterministic synthetic set so training-convergence tests can run anywhere
(labels are a known function of the images, so a model CAN fit them).
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as onp

from ....base import MXNetError, data_dir
from ....ndarray.ndarray import NDArray
from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset"]


def _synthetic_mnist(num: int, seed: int, num_classes: int = 10,
                     template_seed: int = None):
    """Deterministic learnable stand-in: each class is a blurred template
    plus noise. The templates come from ``template_seed`` so train and
    test splits share them (a model trained on one generalizes to the
    other); only labels/noise vary with ``seed``."""
    t_rng = onp.random.RandomState(
        template_seed if template_seed is not None else seed)
    templates = t_rng.rand(num_classes, 28, 28).astype("float32")
    rng = onp.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=num).astype("int32")
    noise = rng.rand(num, 28, 28).astype("float32") * 0.5
    images = templates[labels] + noise
    images = (images / images.max() * 255).astype("uint8")
    return images[..., None], labels


class MNIST(Dataset):
    """MNIST (reference vision.MNIST). Reads idx-ubyte files from ``root``
    when present, else generates the synthetic stand-in."""

    _base_seed = 42
    _subdir = "mnist"

    def __init__(self, root=None, train=True,
                 transform=None):
        if root is None:  # MXNET_HOME-relative default (env_var.md)
            root = os.path.join(data_dir(), "datasets", self._subdir)
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._load()

    def _file_names(self):
        if self._train:
            return ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
        return ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def _load(self):
        img_name, lbl_name = self._file_names()
        img_path = os.path.join(self._root, img_name)
        lbl_path = os.path.join(self._root, lbl_name)
        if os.path.exists(img_path) or os.path.exists(img_path + ".gz"):
            self._data, self._label = self._read_idx(img_path, lbl_path)
        else:
            n = 8000 if self._train else 2000
            self._data, self._label = _synthetic_mnist(
                n, self._base_seed + (0 if self._train else 1),
                template_seed=self._base_seed)

    @staticmethod
    def _read_idx(img_path, lbl_path):
        def opener(p):
            return gzip.open(p + ".gz", "rb") if os.path.exists(p + ".gz") \
                else open(p, "rb")
        with opener(lbl_path) as f:
            magic, num = struct.unpack(">II", f.read(8))
            labels = onp.frombuffer(f.read(), dtype=onp.uint8) \
                .astype("int32")
        with opener(img_path) as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            images = onp.frombuffer(f.read(), dtype=onp.uint8) \
                .reshape(num, rows, cols, 1)
        return images, labels

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        img = NDArray(self._data[idx])
        lbl = int(self._label[idx])
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl


class FashionMNIST(MNIST):
    _base_seed = 77
    _subdir = "fashion-mnist"

    def __init__(self, root=None, train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(Dataset):
    """CIFAR-10 (reference vision.CIFAR10); reads the binary batch format
    from root, else synthesizes 32x32x3 learnable data."""

    _num_classes = 10
    _subdir = "cifar10"

    def __init__(self, root=None, train=True,
                 transform=None):
        if root is None:  # MXNET_HOME-relative default (env_var.md)
            root = os.path.join(data_dir(), "datasets", self._subdir)
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._load()

    def _load(self):
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            datas, labels = [], []
            rec = 1 + 3072 if self._num_classes == 10 else 2 + 3072
            for p in paths:
                raw = onp.fromfile(p, dtype=onp.uint8).reshape(-1, rec)
                labels.append(raw[:, rec - 3073].astype("int32"))
                datas.append(raw[:, rec - 3072:].reshape(-1, 3, 32, 32)
                             .transpose(0, 2, 3, 1))
            self._data = onp.concatenate(datas)
            self._label = onp.concatenate(labels)
        else:
            # templates from a split-independent seed: train and test must
            # share class structure for the data to be learnable
            t_rng = onp.random.RandomState(123 + self._num_classes)
            templates = t_rng.rand(self._num_classes, 32, 32, 3) \
                .astype("float32")
            rng = onp.random.RandomState(123 if self._train else 321)
            n = 4000 if self._train else 1000
            self._label = rng.randint(0, self._num_classes, n).astype("int32")
            imgs = templates[self._label] + \
                rng.rand(n, 32, 32, 3).astype("float32") * 0.5
            self._data = (imgs / imgs.max() * 255).astype("uint8")

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        img = NDArray(self._data[idx])
        lbl = int(self._label[idx])
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl


class CIFAR100(CIFAR10):
    _num_classes = 100
    _subdir = "cifar100"

    def __init__(self, root=None, train=True,
                 transform=None, fine_label=True):
        super().__init__(root, train, transform)


class ImageFolderDataset(Dataset):
    """A folder-of-class-folders image dataset (reference
    ImageFolderDataset); decodes with PIL/numpy on the host."""

    def __init__(self, root: str, flag: int = 1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        if not os.path.isdir(self._root):
            raise MXNetError(f"{self._root} is not a directory")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".bmp",
                                           ".npy")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        from .... import image as mx_image
        img = mx_image.imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageRecordDataset(Dataset):
    """Images in a RecordIO file (reference ImageRecordDataset over
    src/io/dataset.cc:188)."""

    def __init__(self, filename: str, flag: int = 1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio
        from .... import image as mx_image
        raw = self._record[idx]
        header, img_bytes = recordio.unpack(raw)
        img = mx_image.imdecode(img_bytes, self._flag)
        label = int(header.label) if onp.isscalar(header.label) \
            else NDArray(onp.asarray(header.label))
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
