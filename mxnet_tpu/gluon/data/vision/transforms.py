"""Vision transforms (reference: gluon/data/vision/transforms.py).

Transforms are HybridBlocks operating on HWC uint8/float images on the host;
under a Compose chain they run inside the DataLoader workers.
"""
from __future__ import annotations

import numpy as onp

from ....base import MXNetError
from ....ndarray.ndarray import NDArray
from ....ndarray import ops as F
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential

__all__ = ["Compose", "HybridCompose", "Cast", "ToTensor", "Normalize",
           "Resize", "CenterCrop", "CropResize", "RandomResizedCrop",
           "RandomCrop", "RandomApply", "HybridRandomApply",
           "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting",
           "RandomGray", "Rotate", "RandomRotation"]


class Compose(Sequential):
    """Sequentially composed transforms (reference Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class HybridCompose(HybridSequential):
    """Hybrid version of Compose: every member must be a HybridBlock so
    the whole chain fuses into one compiled program (reference
    transforms/__init__.py:80)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            if not isinstance(t, HybridBlock):
                raise ValueError(f"{t} is not a HybridBlock, try use "
                                 "`Compose` instead")
            self.add(t)
        self.hybridize()


class RandomApply(Sequential):
    """Apply ``transforms`` (a Block or composed chain) with probability
    ``p``, decided on host per call (reference
    transforms/__init__.py:138)."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        self.transforms = transforms
        self.p = p

    def forward(self, x):
        if self.p < onp.random.random():
            return x
        return self.transforms(x)


class HybridRandomApply(HybridSequential):
    """Hybrid RandomApply: the coin flip is a device-side uniform draw
    and the branch is a compiled ``lax.cond`` — only the taken branch
    executes (reference transforms/__init__.py:168, which lowers to
    F.contrib.cond the same way)."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        assert isinstance(transforms, HybridBlock), \
            "transforms must be a HybridBlock"
        self.transforms = transforms
        self.p = p

    def forward(self, x):
        from ....ndarray import random as ndrandom
        from ....ndarray import contrib as ndcontrib
        coin = ndrandom.uniform(low=0, high=1, shape=(1,))
        # apply WITH probability p: P(coin <= p) = p (the previous
        # `coin > p` applied with probability 1-p — inverted)
        pred = (coin <= self.p).reshape(())
        return ndcontrib.cond(pred,
                              lambda v: self.transforms(v),
                              lambda v: v, [x])


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference ToTensor)."""

    def forward(self, x):
        arr = x.asnumpy().astype("float32") / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return NDArray(arr)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype="float32")
        self._std = onp.asarray(std, dtype="float32")

    def forward(self, x):
        arr = x.asnumpy()
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return NDArray((arr - mean) / std)


def _resize_np(arr, size):
    """Nearest-neighbor host resize (decode path; avoids device round-trip)."""
    h, w = arr.shape[:2]
    ow, oh = (size, size) if isinstance(size, int) else size
    ys = (onp.arange(oh) * h / oh).astype(int).clip(0, h - 1)
    xs = (onp.arange(ow) * w / ow).astype(int).clip(0, w - 1)
    return arr[ys][:, xs]


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        return NDArray(_resize_np(x.asnumpy(), self._size))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        arr = x.asnumpy()
        h, w = arr.shape[:2]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        out = arr[y0:y0 + ch, x0:x0 + cw]
        if out.shape[:2] != (ch, cw):
            out = _resize_np(arr, self._size)
        return NDArray(out)


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        arr = x.asnumpy()
        if self._pad:
            p = self._pad
            arr = onp.pad(arr, ((p, p), (p, p), (0, 0)), mode="constant")
        h, w = arr.shape[:2]
        cw, ch = self._size
        y0 = onp.random.randint(0, max(h - ch, 0) + 1)
        x0 = onp.random.randint(0, max(w - cw, 0) + 1)
        return NDArray(arr[y0:y0 + ch, x0:x0 + cw])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        arr = x.asnumpy()
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            ar = onp.random.uniform(*self._ratio)
            cw = int(round(onp.sqrt(target_area * ar)))
            ch = int(round(onp.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                x0 = onp.random.randint(0, w - cw + 1)
                y0 = onp.random.randint(0, h - ch + 1)
                crop = arr[y0:y0 + ch, x0:x0 + cw]
                return NDArray(_resize_np(crop, self._size))
        return NDArray(_resize_np(arr, self._size))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return NDArray(x.asnumpy()[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return NDArray(x.asnumpy()[::-1].copy())
        return x


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + onp.random.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32") * self._factor()
        return NDArray(arr)


class RandomContrast(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32")
        mean = arr.mean()
        return NDArray(mean + (arr - mean) * self._factor())


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32")
        gray = arr.mean(axis=-1, keepdims=True)
        return NDArray(gray + (arr - gray) * self._factor())


class RandomHue(_RandomJitter):
    """Random hue rotation (reference transforms RandomHue): chroma-plane
    rotation in YIQ space, same math as image.HueJitterAug."""

    def forward(self, x):
        from ....image.image import HueJitterAug
        return HueJitterAug(self._amount)(x)


class RandomColorJitter(Block):
    """Brightness/contrast/saturation/hue jitter applied in random order
    (reference transforms RandomColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = onp.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[int(i)](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference transforms
    RandomLighting): alpha_std scales N(0,1) draws along the ImageNet RGB
    eigenvectors."""

    _EIGVAL = onp.array([55.46, 4.794, 1.148], "float32")
    _EIGVEC = onp.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], "float32")

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        alpha = onp.random.normal(0, self._alpha, size=(3,))
        rgb = (self._EIGVEC * alpha * self._EIGVAL).sum(axis=1)
        return NDArray(x.asnumpy().astype("float32")
                       + rgb.astype("float32"))


class RandomGray(Block):
    """Convert to 3-channel grayscale with probability p (reference
    transforms RandomGray). Luma weights shared with the image-module
    augmenters (single source of truth)."""

    @property
    def _COEF(self):
        from ....image.image import ContrastJitterAug
        return ContrastJitterAug._COEF

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            arr = x.asnumpy().astype("float32")
            gray = (arr * self._COEF).sum(-1, keepdims=True)
            return NDArray(onp.broadcast_to(gray, arr.shape).copy())
        return x


class Rotate(Block):
    """Rotate a CHW float32 image (or NCHW batch) by a fixed angle,
    keeping the shape (reference transforms/image.py:144; kernel =
    image.imrotate, one fused XLA program)."""

    def __init__(self, rotation_degrees, zoom_in=False, zoom_out=False):
        super().__init__()
        self._args = (rotation_degrees, zoom_in, zoom_out)

    def forward(self, x):
        if str(x.dtype) != "float32":
            raise TypeError("This transformation only supports float32. "
                            "Consider calling it after ToTensor, "
                            f"given: {x.dtype}")
        from ....image.image import imrotate
        deg, zin, zout = self._args
        return imrotate(x, deg, zoom_in=zin, zoom_out=zout)


class RandomRotation(Block):
    """Rotate by an angle drawn uniformly from ``angle_limits``, with
    probability ``rotate_with_proba`` (reference
    transforms/image.py:174)."""

    def __init__(self, angle_limits, zoom_in=False, zoom_out=False,
                 rotate_with_proba=1.0):
        super().__init__()
        lower, upper = angle_limits
        if lower >= upper:
            raise ValueError("`angle_limits` must be an ordered tuple")
        if rotate_with_proba < 0 or rotate_with_proba > 1:
            raise ValueError("Probability of rotating the image should "
                             "be between 0 and 1")
        self._args = (angle_limits, zoom_in, zoom_out)
        self._rotate_with_proba = rotate_with_proba

    def forward(self, x):
        if onp.random.random() > self._rotate_with_proba:
            return x
        if str(x.dtype) != "float32":
            raise TypeError("This transformation only supports float32. "
                            "Consider calling it after ToTensor, "
                            f"given: {x.dtype}")
        from ....image.image import random_rotate
        limits, zin, zout = self._args
        return random_rotate(x, limits, zoom_in=zin, zoom_out=zout)


class CropResize(HybridBlock):
    """Crop a fixed region of an HWC image (or NHWC batch), optionally
    resizing the crop (reference transforms/image.py:259). Static crop
    coordinates keep the whole op traceable: the slice + resize fuse
    into the surrounding compiled program."""

    def __init__(self, x, y, width, height, size=None, interpolation=None):
        super().__init__()
        self._x = int(x)
        self._y = int(y)
        self._width = int(width)
        self._height = int(height)
        if size is not None and not isinstance(size, (tuple, list)):
            size = (size, size)
        self._size = tuple(size) if size is not None else None
        self._interpolation = interpolation

    def forward(self, data):
        from ....ops.registry import invoke_raw
        import jax.numpy as _jnp

        if data.ndim not in (3, 4):
            raise ValueError("CropResize expects (H, W, C) or "
                             f"(N, H, W, C) input, got {data.shape}")
        x0, y0, w, h = self._x, self._y, self._width, self._height
        size, interp = self._size, self._interpolation

        def fn(d):
            import jax
            if d.ndim == 3:
                crop = d[y0:y0 + h, x0:x0 + w, :]
                if size is None:
                    return crop
                method = "nearest" if interp == 0 else "linear"
                return jax.image.resize(
                    crop.astype(_jnp.float32),
                    (size[1], size[0], d.shape[-1]),
                    method=method).astype(d.dtype)
            crop = d[:, y0:y0 + h, x0:x0 + w, :]
            if size is None:
                return crop
            method = "nearest" if interp == 0 else "linear"
            return jax.image.resize(
                crop.astype(_jnp.float32),
                (d.shape[0], size[1], size[0], d.shape[-1]),
                method=method).astype(d.dtype)

        return invoke_raw("crop_resize", fn, [data])
