"""Batchify functions (reference: src/io/batchify.cc + gluon batchify)."""
from __future__ import annotations

import numpy as onp

from ...ndarray.ndarray import NDArray

__all__ = ["Stack", "Pad", "Group"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)


class Stack:
    """Stack samples along a new batch axis (reference StackBatchify)."""

    def __call__(self, data):
        return NDArray(onp.stack([_to_np(d) for d in data]))


class Pad:
    """Pad variable-length samples to the batch max (reference PadBatchify)."""

    def __init__(self, axis=0, val=0, dtype=None):
        self._axis = axis
        self._val = val
        self._dtype = dtype

    def __call__(self, data):
        arrs = [_to_np(d) for d in data]
        max_len = max(a.shape[self._axis] for a in arrs)
        padded = []
        for a in arrs:
            pad_width = [(0, 0)] * a.ndim
            pad_width[self._axis] = (0, max_len - a.shape[self._axis])
            padded.append(onp.pad(a, pad_width, constant_values=self._val))
        out = onp.stack(padded)
        if self._dtype:
            out = out.astype(self._dtype)
        return NDArray(out)


class Group:
    """Apply one batchify fn per field (reference GroupBatchify)."""

    def __init__(self, *fns):
        self._fns = fns

    def __call__(self, data):
        return tuple(fn([d[i] for d in data])
                     for i, fn in enumerate(self._fns))
