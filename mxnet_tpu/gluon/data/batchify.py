"""Batchify functions (reference: src/io/batchify.cc + gluon batchify).

The native C++ backend (src/native/batchify.cc) collates uniform samples
with GIL-free parallel memcpy — the analog of the reference's OMP-parallel
StackBatchify — and fuses the image normalize+HWC→CHW transpose
(reference iter_image_recordio_2.cc decode-side augmenters). Python
fallbacks keep everything working without the library.
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp

from ... import _native
from ...ndarray.ndarray import NDArray

__all__ = ["Stack", "Pad", "Group", "ImageNormalize"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)


# below this many total bytes numpy's single memcpy loop wins over
# spawning worker threads (measured ~3.5x for tiny batches)
_NATIVE_STACK_MIN_BYTES = 1 << 20


def _native_stack(arrs):
    """Parallel native collation of uniform C-contiguous samples; None if
    unavailable, non-uniform, object-typed, or too small to amortize the
    thread spawn."""
    lib = _native.get_lib()
    if lib is None or len(arrs) < 2:
        return None
    first = arrs[0]
    if first.dtype.hasobject:
        return None  # raw-pointer memcpy would corrupt refcounts
    if any(a.shape != first.shape or a.dtype != first.dtype for a in arrs):
        return None
    if first.nbytes * len(arrs) < _NATIVE_STACK_MIN_BYTES:
        return None
    arrs = [onp.ascontiguousarray(a) for a in arrs]
    n = len(arrs)
    out = onp.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
    rc = lib.MXTBatchifyStack(ptrs, n, first.nbytes,
                              ctypes.c_void_p(out.ctypes.data),
                              min(n, os.cpu_count() or 1, 16))
    return out if rc == 0 else None


class Stack:
    """Stack samples along a new batch axis (reference StackBatchify;
    native parallel copy when libmxt_native is built)."""

    def __call__(self, data):
        arrs = [_to_np(d) for d in data]
        native = _native_stack(arrs)
        return NDArray(native if native is not None else onp.stack(arrs))


class Pad:
    """Pad variable-length samples to the batch max (reference PadBatchify)."""

    def __init__(self, axis=0, val=0, dtype=None):
        self._axis = axis
        self._val = val
        self._dtype = dtype

    def __call__(self, data):
        arrs = [_to_np(d) for d in data]
        max_len = max(a.shape[self._axis] for a in arrs)
        padded = []
        for a in arrs:
            pad_width = [(0, 0)] * a.ndim
            pad_width[self._axis] = (0, max_len - a.shape[self._axis])
            padded.append(onp.pad(a, pad_width, constant_values=self._val))
        out = onp.stack(padded)
        if self._dtype:
            out = out.astype(self._dtype)
        return NDArray(out)


class Group:
    """Apply one batchify fn per field (reference GroupBatchify)."""

    def __init__(self, *fns):
        self._fns = fns

    def __call__(self, data):
        return tuple(fn([d[i] for d in data])
                     for i, fn in enumerate(self._fns))


class ImageNormalize:
    """Fused batchify for HWC uint8 images -> normalized NCHW float32
    batch: out[n,c,h,w] = (img[n,h,w,c]/255 - mean[c]) / std[c]. The
    native path runs one sample per C++ thread (the reference decodes +
    normalizes on dmlc worker threads, iter_image_recordio_2.cc); the
    Python fallback vectorizes with numpy."""

    def __init__(self, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)):
        self._mean = onp.asarray(mean, "float32")
        self._std = onp.asarray(std, "float32")

    def __call__(self, data):
        arrs = [onp.ascontiguousarray(_to_np(d)) for d in data]
        first = arrs[0]
        if any(a.ndim != 3 or a.dtype != onp.uint8 for a in arrs):
            raise ValueError("ImageNormalize expects HWC uint8 samples")
        h, w, c = first.shape
        if self._mean.shape[0] != c or self._std.shape[0] != c:
            raise ValueError(
                f"mean has {self._mean.shape[0]} and std has "
                f"{self._std.shape[0]} channels, images have {c}")
        n = len(arrs)
        lib = _native.get_lib()
        if lib is not None and n > 1 and \
                n * first.nbytes >= _NATIVE_STACK_MIN_BYTES and \
                all(a.shape == first.shape for a in arrs):
            out = onp.empty((n, c, h, w), "float32")
            ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
            rc = lib.MXTBatchifyImageNormalize(
                ptrs, n, h, w, c,
                self._mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                min(n, os.cpu_count() or 1, 16))
            if rc == 0:
                return NDArray(out)
        batch = onp.stack(arrs).astype("float32") / 255.0
        batch = (batch - self._mean) / self._std
        return NDArray(batch.transpose(0, 3, 1, 2))
