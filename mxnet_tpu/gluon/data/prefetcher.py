"""Device-side input prefetch: overlap host→device copy with compute.

The threaded ``DataLoader`` pipeline overlaps DECODE with training, but
the final host→device transfer still happened synchronously inside jit
dispatch — the TPU idled on the PCIe/ICI copy every step. This stage
closes that gap (the top non-model optimization of the MLPerf TPU-pod
work, arXiv:1909.09756; reference analog: ``iter_prefetcher.h`` +
``PrefetchingIter``, generalized to place ON the accelerator):

- a bounded background thread pulls batches from any host iterable and
  ``jax.device_put``s them ahead of time — with the train step's EXACT
  ``NamedSharding`` when a mesh is active (dp-sharded batch dim,
  replicated otherwise), so the fused step's input-layout check passes
  them through untouched;
- ``device_put`` is itself async: the producer thread only *enqueues*
  transfers, the PjRt runtime streams them while the chip runs step N;
- the consumer side records how long it actually waited on input
  (``input_wait_ms``) and how often the staging queue was empty on
  arrival (``starvation_count``) — the two numbers that tell a profiler
  whether input is hidden or the bottleneck.

Wiring: ``DataLoader(..., device=..., prefetch_to_device=k)`` or
``TrainLoop.prefetch(batches)`` (which supplies the step's placement).
``MXNET_DEVICE_PREFETCH`` sets the default staging depth (2); 0 disables
the background thread (placement still happens, inline).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as onp

import jax

from ...analysis.threads import mx_lock, register_queue
from ...base import MXNetError
from ...ndarray.ndarray import NDArray

__all__ = ["DevicePrefetcher", "default_prefetch_depth"]

_DONE = object()

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from ... import telemetry as _t
        _TELEM = _t
    return _TELEM


def default_prefetch_depth(default: int = 2) -> int:
    try:
        v = int(os.environ.get("MXNET_DEVICE_PREFETCH", str(default)))
    except ValueError:
        return default
    return max(0, v)


class _Raised:
    """Producer-side exception carrier: re-raised at the consumer."""

    def __init__(self, exc):
        self.exc = exc


class DevicePrefetcher:
    """Bounded background host→device staging over any batch iterable.

    ``place`` is the per-leaf placement (``CompiledTrainStep
    .input_placement()`` — the step's NamedSharding); when ``None``,
    leaves go to ``device`` (a ``Context``, ``jax.Device``, or ``None``
    for the process default). A ``DeviceMesh`` may be passed as
    ``mesh=`` (with ``axis=``) instead of an explicit ``place``.

    Iterating yields batches with the same structure and handle types as
    the source (NDArray in → NDArray out), already device-resident.
    Stats (cumulative across iterations): ``prefetch_batches``,
    ``input_wait_ms``, ``starvation_count``, ``prefetch_depth``.
    """

    def __init__(self, source, depth: Optional[int] = None,
                 place: Optional[Callable] = None, device=None,
                 mesh=None, axis: str = "dp", timeout: float = 120.0):
        self._source = source
        self._depth = default_prefetch_depth() if depth is None \
            else max(0, int(depth))
        self._timeout = timeout
        if place is None and mesh is not None:
            from ...parallel.mesh import place_on_mesh
            place = lambda d, _m=mesh, _a=axis: place_on_mesh(_m, _a, d)  # noqa: E731
        self._place_leaf = place
        self._device = self._resolve_device(device) if place is None \
            else None
        self.stats = {"prefetch_depth": self._depth,
                      "prefetch_batches": 0, "input_wait_ms": 0.0,
                      "starvation_count": 0}
        # stats is a public dict read while the producer thread runs;
        # every mutation goes through this lock so a reader (monitor
        # thread, test assertion) never sees a torn update
        self._stats_mu = mx_lock("data.prefetch.stats")
        t = _telemetry()
        reg = t.registry()
        self._m_batches = reg.counter(t.names.PREFETCH_BATCHES)
        self._m_starved = reg.counter(t.names.PREFETCH_STARVATION)
        self._m_wait = reg.counter(t.names.PREFETCH_INPUT_WAIT)

    @staticmethod
    def _resolve_device(device):
        if device is None or device is True:
            return None   # process-default placement
        if isinstance(device, jax.Device):
            return device
        jd = getattr(device, "jax_device", None)   # mx.Context
        if jd is not None:
            return jd() if callable(jd) else jd
        raise MXNetError(
            f"device= must be a Context, jax.Device, or None; "
            f"got {type(device).__name__}")

    # ---------------- placement ----------------
    def _put(self, d):
        if self._place_leaf is not None:
            return self._place_leaf(d)
        if self._device is None:
            return jax.device_put(d)
        return jax.device_put(d, self._device)

    def _track(self, staged):
        """File one staged device buffer in the census ``prefetch`` pool
        (weakref — it leaves the pool when the consumer drops the
        batch; the early-break release test counts on this)."""
        try:
            _telemetry().memory.census().register("prefetch", staged)
        except Exception:        # pragma: no cover - census must never
            pass                 # kill the producer thread
        return staged

    def _stage_batch(self, batch, ordinal):
        """One whole batch through :meth:`_stage`, bracketed by the
        chaos-harness ``prefetch.stage`` fault point (one hit per BATCH,
        not per leaf — device_put staging is the third seam a mid-run
        device revocation can land on) and the device-lost detector."""
        from ...testing.faults import fault_point
        fault_point("prefetch.stage", "before")
        try:
            staged = self._stage(batch)
        except BaseException as e:
            from ...elastic import detect as _edet
            _edet.maybe_record_device_lost(e, "prefetch staging",
                                           step=ordinal)
            raise
        fault_point("prefetch.stage", "after")
        return staged

    def _stage(self, batch):
        """Recursively device_put a batch, preserving structure and
        handle types (NDArray stays NDArray). Each staged device buffer
        is tracked in the census ``prefetch`` pool."""
        if isinstance(batch, NDArray):
            return self._track(NDArray(self._put(batch._data)))
        if isinstance(batch, (tuple, list)):
            return type(batch)(self._stage(b) for b in batch)
        if isinstance(batch, dict):
            return {k: self._stage(v) for k, v in batch.items()}
        if isinstance(batch, (onp.ndarray, jax.Array)):
            return self._track(self._put(batch))
        return batch

    # ---------------- telemetry ----------------
    def _record_fetch(self, ordinal, t0, t1):
        """batch_fetch span (source pull + device staging) — producer
        side; ordinal is this prefetcher's batch number, the closest
        step attribution the data layer has."""
        t = _telemetry()
        if t.active():
            t.timeline().record("batch_fetch", t0, t1, step=ordinal)

    def _record_wait(self, ordinal, t0, t1):
        """h2d_wait span (consumer blocked on staged input)."""
        with self._stats_mu:
            self.stats["input_wait_ms"] += (t1 - t0) * 1e3
        self._m_wait.inc(t1 - t0)
        t = _telemetry()
        if t.active():
            t.timeline().record("h2d_wait", t0, t1, step=ordinal)

    # ---------------- iteration ----------------
    def __iter__(self):
        if self._depth == 0:
            it = iter(self._source)
            n = 0
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    return
                staged = self._stage_batch(batch, n)
                self._record_fetch(n, t0, time.perf_counter())
                with self._stats_mu:
                    self.stats["prefetch_batches"] += 1
                self._m_batches.inc()
                n += 1
                yield staged
            return

        q: "queue.Queue" = queue.Queue(maxsize=self._depth)
        register_queue("data.prefetch", q)   # visible in thread dumps
        stop = threading.Event()

        def produce():
            try:
                it = iter(self._source)
                n = 0
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    staged = self._stage_batch(batch, n)
                    self._record_fetch(n, t0, time.perf_counter())
                    n += 1
                    while not stop.is_set():
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                item = _DONE
            except BaseException as e:   # noqa: BLE001 - carried across
                item = _Raised(e)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        worker = threading.Thread(target=produce, daemon=True,
                                  name="mx-device-prefetch")
        worker.start()
        try:
            n = 0
            while True:
                if q.empty():
                    with self._stats_mu:
                        self.stats["starvation_count"] += 1
                    self._m_starved.inc()
                t0 = time.perf_counter()
                try:
                    item = q.get(timeout=self._timeout)
                except queue.Empty:
                    raise MXNetError(
                        f"DevicePrefetcher produced no batch within "
                        f"timeout={self._timeout}s") from None
                self._record_wait(n, t0, time.perf_counter())
                if item is _DONE:
                    return
                if isinstance(item, _Raised):
                    # a device_put that exhausted HBM (or lost its
                    # device) is carried here from the producer thread —
                    # record the post-mortem at the seam the user
                    # actually sees (both records are chain-marked:
                    # exactly one event however many seams re-raise)
                    _telemetry().memory.maybe_record_oom(
                        item.exc, "prefetch staging", step=n)
                    from ...elastic import detect as _edet
                    _edet.maybe_record_device_lost(
                        item.exc, "prefetch staging", step=n)
                    raise item.exc
                with self._stats_mu:
                    self.stats["prefetch_batches"] += 1
                self._m_batches.inc()
                n += 1
                yield item
        finally:
            # deterministic staging release: on early break / error the
            # queue still holds up to `depth` staged device batches —
            # stop the producer, then DROP the queued references so the
            # census `prefetch` pool (and HBM) drains immediately
            # instead of at whenever this generator is collected
            stop.set()
            worker.join(timeout=5.0)
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
