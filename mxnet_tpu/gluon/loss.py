"""Loss functions (reference: python/mxnet/gluon/loss.py — 16 classes).

Each Loss is a HybridBlock: forward(pred, label, sample_weight=None) returns
per-sample losses reduced over ``batch_axis`` like the reference.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..ndarray import ops as F
from ..ndarray.ndarray import NDArray
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "TripletLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss", "CTCLoss", "SDMLLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    return label.reshape(pred.shape) if label.shape != pred.shape else label


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_all_but_batch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = F.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean_all_but_batch(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = F.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        err = F.abs(label - pred)
        loss = F.where(err > self._rho,
                       err - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Reference SigmoidBCELoss: numerically-stable BCE on logits."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = F.relu(pred) - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu") +
                     F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label +
                         F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.log(pred + eps) * label * pos_weight +
                         F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference SoftmaxCELoss: fused log-softmax + pick, sparse or dense
    labels."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(pred, label)
            loss = -(pred * label).sum(axis=self._axis, keepdims=True)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (F.square(pred - positive) - F.square(pred - negative)) \
            .sum(axis=tuple(range(1, pred.ndim)))
        loss = F.relu(loss + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        eps = 1e-12
        num = (input1 * input2).sum(axis=1)
        den = F.sqrt((input1 * input1).sum(axis=1) + eps) * \
            F.sqrt((input2 * input2).sum(axis=1) + eps)
        cos = num / den
        label = label.reshape((-1,))
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = target * F.log(target + 1e-12) - target + \
                0.5 * F.log(2 * onp.pi * (target + 1e-12))
            stirling = F.where(target <= 1, F.zeros_like(stirling), stirling)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 \
            else loss


class CTCLoss(Loss):
    """Connectionist temporal classification (reference CTCLoss over
    src/operator/nn/ctc_loss.cc / vendored ctc_include). Implemented with the
    standard alpha-recursion in log space via lax.scan — sequential in T but
    vectorized over batch/labels on the MXU."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        import jax
        import jax.numpy as jnp
        from ..ops.registry import invoke_raw as _inv
        from ..ndarray.ndarray import NDArray as _ND

        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        B, T, C = pred.shape
        L = label.shape[1]
        inputs = [pred, label]
        if pred_lengths is not None:
            inputs.append(pred_lengths)
        if label_lengths is not None:
            inputs.append(label_lengths)

        def fn(p, lab, *lens):
            plen = lens[0].astype(jnp.int32) if pred_lengths is not None \
                else jnp.full((B,), T, jnp.int32)
            rest = lens[1:] if pred_lengths is not None else lens
            llen = rest[0].astype(jnp.int32) if label_lengths is not None \
                else jnp.sum((lab != 0).astype(jnp.int32), axis=1)
            logp = jax.nn.log_softmax(p, axis=-1)
            blank = 0
            lab = lab.astype(jnp.int32)
            # extended label seq: blank, l1, blank, l2, ... blank (2L+1)
            ext = jnp.full((B, 2 * L + 1), blank, jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            S = 2 * L + 1
            neg_inf = -1e30
            # can-skip mask: s>=2 and ext[s] != blank and ext[s] != ext[s-2]
            idx = jnp.arange(S)
            skip_ok = (idx[None, :] >= 2) & (ext != blank) & \
                (ext != jnp.roll(ext, 2, axis=1))
            alpha0 = jnp.full((B, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(llen > 0, jnp.take_along_axis(
                    logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0], neg_inf))

            def step(alpha, t):
                lp = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
                a1 = jnp.roll(alpha, 1, axis=1).at[:, 0].set(neg_inf)
                a2 = jnp.roll(alpha, 2, axis=1).at[:, :2].set(neg_inf)
                a2 = jnp.where(skip_ok, a2, neg_inf)
                m = jnp.maximum(jnp.maximum(alpha, a1), a2)
                new = m + jnp.log(
                    jnp.exp(alpha - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
                new = new + lp
                # freeze past pred_length
                new = jnp.where((t < plen)[:, None], new, alpha)
                return new, None

            alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
            send = 2 * llen  # index of final blank
            a_end = jnp.take_along_axis(alpha, send[:, None], axis=1)[:, 0]
            a_end1 = jnp.take_along_axis(
                alpha, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
            m = jnp.maximum(a_end, a_end1)
            ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_end1 - m))
            return -ll
        loss = _inv("ctc_loss", fn, inputs)
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference SDMLLoss)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smooth = smoothing_parameter

    def forward(self, x1, x2, sample_weight=None):
        import jax.numpy as jnp
        from ..ops.registry import invoke_raw as _inv
        N = x1.shape[0]

        import jax

        def fn(a, b):
            # pairwise euclidean distances
            d = jnp.sqrt(jnp.sum(
                (a[:, None, :] - b[None, :, :]) ** 2, axis=-1) + 1e-12)
            logits = -d
            labels = jnp.eye(N) * (1 - self._smooth) + \
                (1 - jnp.eye(N)) * self._smooth / (N - 1)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -(labels * logp).sum(axis=1)
        return _inv("sdml_loss", fn, [x1, x2])
