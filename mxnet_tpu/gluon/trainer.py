"""Gluon Trainer: optimizer application + data-parallel gradient reduction.

Reference analog: python/mxnet/gluon/trainer.py (_init_kvstore :188 decision
matrix, step :334 = allreduce + update, update :411). The TPU-native
difference is in what "allreduce" means: with one logical array per Parameter
(possibly mesh-sharded), reduction over devices is either a no-op (replicated
arrays under pjit get psum'ed by XLA inside the step) or a kvstore pushpull
for reference-style per-device replica lists.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..kvstore import kvstore as kvs_mod
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, dict):
            param_items = sorted(params.items())
            self._params = [p for _, p in param_items]
            self._param_names = [k for k, _ in param_items]
        elif isinstance(params, (list, tuple)):
            self._params = list(params)
            self._param_names = [p.name for p in params]
        else:
            raise MXNetError("params must be a dict or list of Parameters")
        # full set incl. grad_req='null' (running stats): the fused whole-
        # step program (compile_step) must bind these as traced state too
        self._all_params = list(self._params)
        self._params = [p for p in self._params if p.grad_req != "null"]
        self._param2idx = {id(p): i for i, p in enumerate(self._params)}

        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = dict(enumerate(self._params))
        self._updater = opt_mod.get_updater(self._optimizer)

        self._kvstore_kind = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._compression_params = compression_params
        self._kv_initialized = False
        self._scale = 1.0
        self._contains_sparse = False
        # live CompiledTrainStep programs built from this trainer: the
        # checkpoint stack asks them whether a ZeRO plan owns the
        # optimizer state (weakrefs — a dropped step must not leak)
        self._compiled_refs: List[weakref.ref] = []
        # fp32 masters restored from a checkpoint, consumed when the
        # next _ZeroShardPlan materializes (checkpoint/state.py)
        self._restored_masters: Dict[int, object] = {}

    # ---------------- properties ----------------
    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    @learning_rate.setter
    def learning_rate(self, lr):
        self._optimizer.learning_rate = lr

    def set_learning_rate(self, lr):
        self._optimizer.learning_rate = lr

    @property
    def optimizer(self):
        return self._optimizer

    # ---------------- fused whole-step compilation ----------------
    def compile_step(self, loss_fn, donate: bool = True,
                     train_mode: bool = True,
                     zero_shard: Optional[bool] = None,
                     zero_axis: str = "dp", mesh=None,
                     analyze: Optional[str] = None,
                     numerics: Optional[str] = None,
                     autotune: Optional[str] = None):
        """Compile the ENTIRE training step — forward, backward, gradient
        reduction, optimizer update — into one donated-buffer XLA program
        per input-shape bucket (gluon/fused_step.py)::

            step = trainer.compile_step(lambda x, y: loss_blk(net(x), y))
            for x, y in batches:
                loss = step(x, y)          # == record/backward/step(bs)

        The returned loss is an ASYNC NDArray — the call dispatches and
        returns while the device works; reading it (``float``,
        ``asnumpy``) is the sync point. Pair with ``gluon.TrainLoop``
        for the bounded in-flight dispatch window
        (``MXNET_INFLIGHT_STEPS``) and device input prefetch
        (``loop.prefetch`` / ``DataLoader(device=...)``) that keep the
        host a fixed number of steps ahead of the chip
        (docs/PERF_NOTES.md "async engine").

        Gradient semantics match ``loss.backward()`` (seed ones) followed
        by ``trainer.step(batch_size)`` with ``batch_size`` inferred from
        the leading batch axis (override per call:
        ``step(x, y, batch_size=n)``). lr/wd/update-count/rescale are
        traced arguments — mutating ``trainer.learning_rate`` or varying
        the batch size never recompiles. Sparse-grad parameters,
        ``update_on_kvstore`` stores, and non-traceable forwards fall
        back transparently to the eager tape path.

        **ZeRO-1 sharded update** (arXiv:2004.13336): when a
        ``parallel.DeviceMesh`` with a ``zero_axis`` ('dp') axis of size
        N >= 2 is active — or passed via ``mesh=`` — the redundant
        replicated weight update is cross-replica sharded: gradients
        reduce-scatter, each replica updates its 1/N flat shard against
        permanently-NamedSharding-sharded optimizer state (momenta, Adam
        moments, fp32 masters of multi-precision params), and the new
        weights all-gather back. Per-replica optimizer-state memory
        drops ~N×. ``zero_shard``: None = auto-detect, True = require
        (raises if no mesh), False = keep the plain in-program psum.
        Parameters below ``MXNET_ZERO_SHARD_MIN_SIZE`` elements bucket
        into one fused shard per dtype (docs/PERF_NOTES.md).

        **Program analysis** (``analyze=`` — docs/ANALYSIS.md): after
        the first step, run the ``mx.analysis`` program lint over the
        compiled program (collective census, donation audit, host
        transfers, dtype drift).  ``'report'`` stores the ProgramReport
        on ``step.analysis_report``, ``'warn'`` also logs findings,
        ``'raise'`` raises on error-severity findings.  Default comes
        from ``MXNET_ANALYSIS``.

        **Numerics observability** (``numerics=`` — docs/OBSERVABILITY
        .md "numerics"): ``'global'`` threads global grad/param norms,
        the update/weight ratio, and per-dtype non-finite counts
        through the compiled program as auxiliary outputs (bit-exact on
        params/loss, psum-composed under ZeRO so shards report true
        global norms); ``'per_layer'`` adds a per-parameter norm vector
        (costlier — see the docs note). The statistics retire sync-free
        through the TrainLoop's dispatch window, feed the
        ``mx_numerics_*`` series and the divergence watchdog
        (grad_spike / nonfinite_grad / update_ratio / master_drift
        anomalies), and a non-finite gradient triggers NaN-origin
        forensics plus an atomic post-mortem dump
        (``MXNET_NUMERICS_DUMP_DIR``). Default comes from
        ``MXNET_NUMERICS``.

        **Self-tuning autopilot** (``autotune=`` — docs/PERF_NOTES.md
        "Autotuner"): on the step's FIRST call (a real batch pins the
        shape bucket), replay this program's cached tuned config with
        zero trials (``'cached'``), or measure-and-search the
        registered tunable space and persist the winner
        (``'on'``; budget ``MXNET_AUTOTUNE_BUDGET_TRIALS``, DB
        ``MXNET_AUTOTUNE_CACHE``). Tunables never change numerics —
        only speed. Default comes from ``MXNET_AUTOTUNE`` (off).
        """
        from .fused_step import CompiledTrainStep
        return CompiledTrainStep(self, loss_fn, donate=donate,
                                 train_mode=train_mode,
                                 zero_shard=zero_shard,
                                 zero_axis=zero_axis, mesh=mesh,
                                 analyze=analyze, numerics=numerics,
                                 autotune=autotune)

    # ---------------- compiled-step registry ----------------
    def _register_compiled(self, step):
        self._compiled_refs.append(weakref.ref(step))

    def _live_compiled_steps(self):
        alive, out = [], []
        for ref in self._compiled_refs:
            s = ref()
            if s is not None:
                alive.append(ref)
                out.append(s)
        self._compiled_refs = alive
        return out

    def _zero_state_owner(self):
        """The CompiledTrainStep whose ZeRO plan owns (or will own) the
        sharded optimizer state, if any."""
        for s in self._live_compiled_steps():
            if getattr(s, "_zero", None) is not None or \
                    getattr(s, "_zero_ok", None) is not None:
                return s
        return None

    # ---------------- kvstore setup (reference trainer.py:188) -------------
    def _init_kvstore(self):
        if self._kvstore_kind is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            self._kvstore = kvs_mod.create(self._kvstore_kind) \
                if isinstance(self._kvstore_kind, str) else self._kvstore_kind
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore is None:
                import os
                env = os.environ.get("MXNET_UPDATE_ON_KVSTORE")
                if env is not None:
                    # reference trainer.py honors this override in its
                    # decision matrix (env_var.md MXNET_UPDATE_ON_KVSTORE)
                    self._update_on_kvstore = \
                        env.lower() not in ("0", "false", "no", "")
                else:
                    # single-worker: updating locally is cheaper; dist sync
                    # stores traditionally update on store
                    self._update_on_kvstore = \
                        self._kvstore.num_workers > 1 and \
                        "dist" in getattr(self._kvstore, "type", "")
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            # seed store with current weights
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())
        self._kv_initialized = True

    # ---------------- core ----------------
    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """allreduce gradients then apply optimizer
        (reference trainer.py:334)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        # the store's one-host-sync-per-step IS the design here — bless
        # it for the transfer guard so MXNET_TRANSFER_GUARD only flags
        # UNexpected syncs (analysis/guard.py)
        from ..analysis.guard import allow_transfers
        with allow_transfers("kvstore gradient reduction"):
            if not self._update_on_kvstore:
                # one fused multi-key call: a dist store packs the
                # collectives into buckets and pays ONE host sync per
                # step instead of one per parameter (pushpull_list)
                keys = list(range(len(self._params)))
                self._kvstore.pushpull_list(
                    keys, [p.list_grad() for p in self._params])
                return
            for i, p in enumerate(self._params):
                self._kvstore.push(i, p.list_grad())

    def update(self, batch_size: int, ignore_stale_grad: bool = False):
        """Apply optimizer only (grads assumed reduced;
        reference trainer.py:411)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        # batch all live params into ONE fused updater call (the
        # reference's multi-tensor update, optimizer_op.cc multi_sgd_*)
        idxs, grads, datas = [], [], []
        for i, p in enumerate(self._params):
            if self._update_on_kvstore:
                # store ran the optimizer during push; pull fresh weights
                self._kvstore.pull(i, p.list_data())
                continue
            data = p.data()
            if p.grad_req != "null" and data.grad is not None \
                    and not data.fresh_grad:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"gradient of parameter {p.name} has not been "
                        "updated by backward since the last step; set "
                        "ignore_stale_grad=True to suppress")
                # reference trainer.py skips stale params entirely rather
                # than re-applying the old gradient
                continue
            idxs.append(i)
            grads.append(p.grad())
            datas.append(data)
        if not idxs:
            return
        if len(idxs) == len(self._params):  # _params already excludes null
            self._updater(idxs, grads, datas)   # fused: one XLA dispatch
        else:
            # stale/partial subset: per-param path — a fused program keyed
            # on this exact subset would recompile per distinct subset
            for i, g, d in zip(idxs, grads, datas):
                self._updater(i, g, d)
        for d in datas:
            d.fresh_grad = False

    # ---------------- persistence (reference trainer.py:477,506) -----------
    def train_state(self, step: int = 0, net=None, extra=None):
        """Snapshot the COMPLETE training state — params, optimizer state
        (including fused and ZeRO-sharded buffers that live inside a
        ``compile_step`` program), update counters, lr-scheduler state,
        RNG key — as a ``mx.checkpoint.TrainState`` of host arrays. Pair
        with ``mx.checkpoint.write_checkpoint``/``TrainCheckpointManager``
        for atomic on-disk persistence."""
        from ..checkpoint.state import capture_train_state
        return capture_train_state(trainer=self, net=net, step=step,
                                   extra=extra)

    def load_train_state(self, state, net=None, strict: bool = True):
        """Restore a ``TrainState`` (inverse of :meth:`train_state`);
        returns its meta dict (incl. ``'step'``)."""
        from ..checkpoint.state import apply_train_state
        return apply_train_state(state, trainer=self, net=net,
                                 strict=strict)

    def save_states(self, fname: str):
        """Reference single-file optimizer-state dump. The write is
        crash-safe (staged + fsync + ``os.replace``) but the FORMAT only
        covers the eager updater: when a ZeRO-sharded ``compile_step``
        owns NamedSharding-sharded moments/masters this raises instead
        of silently writing stale state — use :meth:`train_state` /
        ``mx.checkpoint.TrainCheckpointManager`` there."""
        owner = self._zero_state_owner()
        if owner is not None:
            raise MXNetError(
                "Trainer.save_states cannot serialize the ZeRO-sharded "
                "optimizer state owned by a compile_step program (the "
                "eager updater it pickles no longer holds the live "
                "momenta/moments/fp32 masters). Use trainer.train_state()"
                " with mx.checkpoint.write_checkpoint, or "
                "mx.checkpoint.TrainCheckpointManager / "
                "gluon.TrainLoop(checkpoint_dir=...).")
        from ..checkpoint.atomic import atomic_write_bytes
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            atomic_write_bytes(
                fname, self._updater.get_states(dump_optimizer=True),
                fault="trainer.save_states")

    def load_states(self, fname: str):
        """Reads both the single-file updater pickle (reference format,
        still what :meth:`save_states` writes) and — shim for the new
        world — an atomic checkpoint directory produced by
        ``mx.checkpoint`` (its optimizer state + counters are applied)."""
        import os
        if not self._kv_initialized:
            self._init_kvstore()
        if os.path.isdir(fname):
            from ..checkpoint.atomic import read_checkpoint
            from ..checkpoint.state import TrainState, apply_train_state
            arrays, manifest = read_checkpoint(fname)
            state = TrainState(arrays, manifest.get("meta", {}),
                               array_meta=manifest["arrays"])
            apply_train_state(state, trainer=self, strict=False)
            return
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
