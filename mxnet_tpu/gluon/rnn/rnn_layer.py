"""Fused Gluon recurrent layers: RNN / LSTM / GRU.

Reference analog: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer backed by the
monolithic ``RNN`` op with a packed parameter vector). TPU-native design:
parameters stay as separate per-layer/direction arrays (no packing — XLA
fuses the projections anyway) and the recurrence is ops/rnn.py's
``fused_rnn``: one MXU matmul for all input projections + ``lax.scan`` for the
sequential part. Parameter names match the reference
(``{l|r}{k}_{i2h|h2h}_{weight|bias}``) so converted checkpoints load.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as onp

from ...base import MXNetError
from ...ndarray import ndarray as ndmod
from ...ndarray.ndarray import NDArray
from ...ndarray.random import next_key
from ...ops import rnn as rnn_ops
from ...ops.registry import invoke_raw
from ... import _tape
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; TNC or NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        g = rnn_ops.GATES[mode]
        ng = g * hidden_size
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * self._dir
            for d, pre in zip(range(self._dir), ("l", "r")):
                name = f"{pre}{layer}"
                setattr(self, f"{name}_i2h_weight", Parameter(
                    f"{name}_i2h_weight", shape=(ng, in_sz), dtype=dtype,
                    init=i2h_weight_initializer))
                setattr(self, f"{name}_h2h_weight", Parameter(
                    f"{name}_h2h_weight", shape=(ng, hidden_size), dtype=dtype,
                    init=h2h_weight_initializer))
                setattr(self, f"{name}_i2h_bias", Parameter(
                    f"{name}_i2h_bias", shape=(ng,), dtype=dtype,
                    init=i2h_bias_initializer))
                setattr(self, f"{name}_h2h_bias", Parameter(
                    f"{name}_h2h_bias", shape=(ng,), dtype=dtype,
                    init=h2h_bias_initializer))

    def _ordered_params(self) -> List[Parameter]:
        out = []
        for layer in range(self._num_layers):
            for pre in ("l", "r")[:self._dir]:
                for sfx in ("i2h_weight", "h2h_weight", "i2h_bias",
                            "h2h_bias"):
                    out.append(getattr(self, f"{pre}{layer}_{sfx}"))
        return out

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape, "__layout__": "LNC"},
                    {"shape": shape, "__layout__": "LNC"}]
        return [{"shape": shape, "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or ndmod.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def _infer(self, x):
        if self._input_size == 0:
            in_sz = x.shape[-1]
            self._input_size = in_sz
            for pre in ("l", "r")[:self._dir]:
                w = getattr(self, f"{pre}0_i2h_weight")
                w.shape = (w.shape[0], in_sz)
        for p in self._ordered_params():
            if p._data is None and p._deferred_init_args is not None:
                p._finish_deferred_init()

    def forward(self, inputs, states=None):
        """inputs: (T, N, C) for TNC / (N, T, C) for NTC. Returns output, or
        (output, states_out) when states were passed (reference
        rnn_layer.py forward contract)."""
        x = inputs
        if self._layout == "NTC":
            x = x.transpose((1, 0, 2))
        self._infer(x)
        batch = x.shape[1]
        ret_states = states is not None
        if states is None:
            states = self.begin_state(batch, dtype=str(x.dtype))
        elif isinstance(states, NDArray):
            states = [states]
        params = self._ordered_params()
        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" else None
        train = _tape.is_training()
        key = next_key() if (train and self._dropout > 0) else None

        mode, nl, bi, dr = (self._mode, self._num_layers, self._dir == 2,
                            self._dropout)
        n_state = 2 if mode == "lstm" else 1

        def fn(x_, h0_, *rest):
            if mode == "lstm":
                c0_, *pk = rest
            else:
                c0_, pk = None, list(rest)
            if key is not None:
                *pd, k = pk
            else:
                pd, k = list(pk), None
            y, h, c = rnn_ops.fused_rnn(x_, h0_, c0_, pd, mode, nl, bi,
                                        dropout=dr, train=train, key=k)
            return (y, h, c) if c is not None else (y, h)

        inputs_nd = [x, h0] + ([c0] if mode == "lstm" else []) + \
            [p.data() for p in params] + ([NDArray(key)] if key is not None
                                          else [])
        res = invoke_raw(f"rnn_{mode}", fn, inputs_nd,
                         n_outputs=1 + n_state)
        y, out_states = res[0], list(res[1:])
        if self._layout == "NTC":
            y = y.transpose((1, 0, 2))
        return (y, out_states) if ret_states else y

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size} -> "
                f"{self._hidden_size}, {self._layout}, "
                f"num_layers={self._num_layers}"
                f"{', bidirectional' if self._dir == 2 else ''})")


class RNN(_RNNLayer):
    """Vanilla Elman RNN (reference gluon.rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(mode, hidden_size, num_layers, layout, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference gluon.rnn.LSTM; gate order i,f,g,o
    matches src/operator/rnn_impl.h)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU (reference gluon.rnn.GRU; gate order r,z,n)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, **kwargs)
