"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are ordinary HybridBlocks stepping one timestep; ``unroll`` is a Python
loop — under ``hybridize()`` the loop is unrolled into one XLA computation
(static sequence length), the TPU-idiomatic equivalent of the reference's
symbolic unroll. For long sequences prefer the fused layers (rnn_layer.py)
whose ``lax.scan`` compiles the body once.
"""
from __future__ import annotations

from typing import List, Optional

from ...base import MXNetError
from ...ndarray import ndarray as ndmod
from ...ndarray import ops as F
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of (N, C) steps or a merged tensor."""
    t_axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        steps = list(inputs)
        if length is not None and len(steps) != length:
            raise MXNetError(f"expected {length} steps, got {len(steps)}")
        merged = None
    else:
        merged = inputs
        if length is None:
            length = inputs.shape[t_axis]
        steps = [inputs.take(i, axis=t_axis) for i in range(length)]
    if merge:
        stacked = F.stack(*steps, axis=t_axis)
        return stacked, length, t_axis
    return steps, length, t_axis


class RecurrentCell(HybridBlock):
    """Base cell: ``cell(input, states) -> (output, new_states)``."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or ndmod.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def reset(self):
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Run the cell over ``length`` steps (reference rnn_cell.py unroll)."""
        steps, length, t_axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            batch = steps[0].shape[0]
            begin_state = self.begin_state(batch, dtype=str(steps[0].dtype))
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(steps[i], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = F.stack(*outputs, axis=0)  # (T, N, C)
            masked = F.SequenceMask(stacked, sequence_length=valid_length,
                                    use_sequence_length=True, value=0.0)
            outputs = [masked.take(i, axis=0) for i in range(length)]
        if merge_outputs:
            return F.stack(*outputs, axis=t_axis), states
        return outputs, states


# Reference splits RecurrentCell/HybridRecurrentCell by hybridizability;
# every cell here is traceable, so they are one class (rnn_cell.py:330).
HybridRecurrentCell = RecurrentCell


class _BaseRNNCell(RecurrentCell):
    """Shared parameter plumbing for the three gated cells."""

    _gates = 1

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = self._gates * hidden_size
        self.i2h_weight = Parameter("i2h_weight", shape=(ng, input_size),
                                    dtype=dtype, init=i2h_weight_initializer)
        self.h2h_weight = Parameter("h2h_weight", shape=(ng, hidden_size),
                                    dtype=dtype, init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(ng,), dtype=dtype,
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(ng,), dtype=dtype,
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _fused_mode(self) -> Optional[str]:
        """ops/rnn.py mode string when this EXACT cell class's step
        math matches the fused recurrence (None: keep the step loop).
        Subclasses/modifier cells override forward, so only the three
        plain gated cells qualify."""
        return None

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroller dispatch: a plain gated cell over a merged (N, T,
        C)/(T, N, C) tensor routes through the SAME fused recurrence
        as the rnn_layer.py layers (one MXU matmul for all input
        projections + the time-fused Pallas scan kernel / lax.scan
        reference, per the MXNET_PALLAS gate) instead of a Python
        step loop — identical math and step semantics (the parity is
        pinned by tests). Step lists, valid_length masking and
        modifier cells keep the reference loop."""
        mode = self._fused_mode()
        if (mode is None or valid_length is not None
                or not isinstance(inputs, NDArray)
                or getattr(inputs, "ndim", 0) != 3
                or layout not in ("NTC", "TNC")):
            return super().unroll(length, inputs, begin_state, layout,
                                  merge_outputs, valid_length)
        from ...ops import rnn as rnn_ops
        from ...ops.registry import invoke_raw
        x = inputs
        t_axis = layout.find("T")
        if layout == "NTC":
            x = x.transpose((1, 0, 2))
        if length is not None and x.shape[0] != length:
            raise MXNetError(
                f"expected {length} steps, got {x.shape[0]}")
        if self._input_size == 0:
            self._input_size = x.shape[-1]
            self.i2h_weight.shape = (self.i2h_weight.shape[0],
                                     x.shape[-1])
        try:
            pd = [p.data() for p in (self.i2h_weight, self.h2h_weight,
                                     self.i2h_bias, self.h2h_bias)]
        except Exception:   # deferred init: the step loop infers it
            return super().unroll(length, inputs, begin_state, layout,
                                  merge_outputs, valid_length)
        batch = x.shape[1]
        if begin_state is None:
            begin_state = self.begin_state(batch, dtype=str(x.dtype))
        lstm = mode == "lstm"
        h0 = begin_state[0].reshape((1,) + tuple(begin_state[0].shape))
        c0 = begin_state[1].reshape(h0.shape) if lstm else None

        def fn(x_, h0_, *rest):
            if lstm:
                c0_, *pk = rest
            else:
                c0_, pk = None, list(rest)
            y, h, c = rnn_ops.fused_rnn(x_, h0_, c0_, pk, mode, 1,
                                        False)
            return (y, h, c) if c is not None else (y, h)

        n_state = 2 if lstm else 1
        res = invoke_raw(f"rnn_{mode}_unroll", fn,
                         [x, h0] + ([c0] if lstm else []) + pd,
                         n_outputs=1 + n_state)
        y, out_states = res[0], [s.reshape(tuple(s.shape[1:]))
                                 for s in res[1:]]
        if layout == "NTC":
            y = y.transpose((1, 0, 2))
        if merge_outputs:
            return y, out_states
        return ([y.take(i, axis=t_axis) for i in range(y.shape[t_axis])],
                out_states)

    def _proj(self, x, h):
        if self._input_size == 0:
            self._input_size = x.shape[-1]
            self.i2h_weight.shape = (self.i2h_weight.shape[0], x.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._data is None and p._deferred_init_args is not None:
                p._finish_deferred_init()
        i2h = F.FullyConnected(x, self.i2h_weight.data(),
                               self.i2h_bias.data(),
                               num_hidden=self.i2h_weight.shape[0])
        h2h = F.FullyConnected(h, self.h2h_weight.data(),
                               self.h2h_bias.data(),
                               num_hidden=self.h2h_weight.shape[0])
        return i2h, h2h


class RNNCell(_BaseRNNCell):
    """Elman cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    _gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    def _fused_mode(self):
        if type(self) is RNNCell and self._activation in ("tanh",
                                                          "relu"):
            return f"rnn_{self._activation}"
        return None

    def forward(self, inputs, states):
        i2h, h2h = self._proj(inputs, states[0])
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseRNNCell):
    """LSTM cell, gate order [i, f, g, o] (reference rnn_cell.py LSTMCell)."""

    _gates = 4

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _fused_mode(self):
        return "lstm" if type(self) is LSTMCell else None

    def forward(self, inputs, states):
        h, c = states
        i2h, h2h = self._proj(inputs, h)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseRNNCell):
    """GRU cell, gate order [r, z, n] (reference rnn_cell.py GRUCell)."""

    _gates = 3

    def _fused_mode(self):
        return "gru" if type(self) is GRUCell else None

    def forward(self, inputs, states):
        h = states[0]
        i2h, h2h = self._proj(inputs, h)
        xr, xz, xn = F.split(i2h, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    """Stack cells; states concatenate (reference SequentialRNNCell)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cells: List[RecurrentCell] = []

    def add(self, cell):
        self._cells.append(cell)
        self.register_child(cell, str(len(self._cells) - 1))

    def state_info(self, batch_size=0):
        return _cells_state_info(self._cells, batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._cells, batch_size=batch_size, **kwargs)

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[pos:pos + n])
            next_states.extend(st)
            pos += n
        return inputs, next_states

    def __len__(self):
        return len(self._cells)

    def __getitem__(self, i):
        return self._cells[i]


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybridizable stacked cells (reference HybridSequentialRNNCell).
    In this build SequentialRNNCell is already trace-compatible (every cell
    op funnels through jit-able kernels), so this is the same machinery
    under the reference's name."""


class DropoutCell(RecurrentCell):
    """Apply dropout to the input (reference DropoutCell)."""

    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate)
        return inputs, states


class ModifierCell(RecurrentCell):
    """Base for cells that wrap another cell to modify its computation
    (reference rnn_cell.py ModifierCell): parameters belong to the base
    cell; state handling delegates to it."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size=batch_size, **kwargs)

    def __repr__(self):
        return f"{type(self).__name__}({self.base_cell!r})"


class ZoneoutCell(ModifierCell):
    """Zoneout regularization wrapper (reference ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)

        def mask(rate, like):
            return F.Dropout(F.ones_like(like), p=rate)

        prev = self._prev_output
        if prev is None:
            prev = F.zeros_like(out)
        if self._zoneout_outputs > 0:
            m = mask(self._zoneout_outputs, out)
            out = F.where(m, out, prev)
        if self._zoneout_states > 0:
            next_states = [F.where(mask(self._zoneout_states, ns), ns, s)
                           for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    """Add the input to the base cell's output (reference ResidualCell)."""

    def forward(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        return out + inputs, next_states


class BidirectionalCell(RecurrentCell):
    """Run two cells over the sequence in opposite directions; only usable
    via ``unroll`` (reference BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return _cells_state_info([self.l_cell, self.r_cell], batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state([self.l_cell, self.r_cell],
                                  batch_size=batch_size, **kwargs)

    def forward(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        steps, length, t_axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            batch = steps[0].shape[0]
            begin_state = self.begin_state(batch, dtype=str(steps[0].dtype))
        n_l = len(self.l_cell.state_info())
        l_states, r_states = begin_state[:n_l], begin_state[n_l:]
        l_out, l_states = self.l_cell.unroll(
            length, steps, l_states, layout="TNC" if t_axis == 0 else "NTC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            rev = F.SequenceReverse(F.stack(*steps, axis=0),
                                    sequence_length=valid_length,
                                    use_sequence_length=True)
            rev_steps = [rev.take(i, axis=0) for i in range(length)]
        else:
            rev_steps = steps[::-1]
        r_out, r_states = self.r_cell.unroll(
            length, rev_steps, r_states,
            layout="TNC" if t_axis == 0 else "NTC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            r_stacked = F.SequenceReverse(F.stack(*r_out, axis=0),
                                          sequence_length=valid_length,
                                          use_sequence_length=True)
            r_out = [r_stacked.take(i, axis=0) for i in range(length)]
        else:
            r_out = r_out[::-1]
        outputs = [F.concat(lo, ro, dim=-1) for lo, ro in zip(l_out, r_out)]
        if merge_outputs:
            return F.stack(*outputs, axis=t_axis), l_states + r_states
        return outputs, l_states + r_states
