"""Gluon recurrent API (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ZoneoutCell,
                       ResidualCell, BidirectionalCell)
from .rnn_layer import RNN, LSTM, GRU
