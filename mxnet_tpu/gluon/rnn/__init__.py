"""Gluon recurrent API (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell,
                       LSTMCell, GRUCell,
                       SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ModifierCell, ZoneoutCell,
                       ResidualCell, BidirectionalCell)
from .rnn_layer import RNN, LSTM, GRU
