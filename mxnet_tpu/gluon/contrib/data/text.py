"""Language-model text datasets: WikiText2 / WikiText103.

Reference analog: python/mxnet/gluon/contrib/data/text.py (:104
WikiText2, :142 WikiText103) — same construction: read the segment's
token file, append ``<eos>`` per line, index through a
``contrib.text.Vocabulary`` (built from the corpus when none is given),
and expose (data, label) = (tokens[:-1], tokens[1:]) reshaped to
``seq_len`` windows.

Environment difference: no egress, so nothing is downloaded. The
dataset looks for the official token files (``wiki.train.tokens`` etc.)
under ``root``; when absent it falls back to a small deterministic
synthetic corpus so pipelines remain runnable end-to-end, and records
which source was used in ``.source``.
"""
import os

import numpy as onp

from .... import ndarray as nd
from ....base import data_dir
from ....contrib import text
from ...data import dataset

__all__ = ["WikiText2", "WikiText103"]

EOS_TOKEN = "<eos>"

_SYNTHETIC_SENTENCES = [
    "the quick brown fox jumps over the lazy dog",
    "language modeling predicts the next token in a sequence",
    "wikitext is a collection of articles from wikipedia",
    "the model reads tokens and learns long term dependencies",
    "a vocabulary maps tokens to integer indices",
    "training minimizes the negative log likelihood of the corpus",
    "the quick brown fox returns because corpora repeat words",
    "evaluation uses perplexity on the held out segments",
]


class _WikiText(dataset.Dataset):
    _segments = ("train", "validation", "test")

    def __init__(self, root, namespace, segment, vocab, seq_len):
        if segment not in self._segments:
            raise ValueError(f"segment must be one of {self._segments}, "
                             f"got {segment!r}")
        self._root = os.path.expanduser(root)
        self._namespace = namespace
        self._segment = segment
        self._vocab = vocab
        self._seq_len = seq_len
        self._counter = None
        self.source = None  # 'file' or 'synthetic'
        self._load()

    @property
    def vocabulary(self):
        return self._vocab

    @property
    def frequencies(self):
        return self._counter

    def _content(self):
        fname = {"train": "wiki.train.tokens",
                 "validation": "wiki.valid.tokens",
                 "test": "wiki.test.tokens"}[self._segment]
        path = os.path.join(self._root, fname)
        if os.path.isfile(path):
            self.source = "file"
            with open(path, "r", encoding="utf8") as f:
                return f.read()
        # deterministic synthetic fallback, segment-dependent slice
        self.source = "synthetic"
        reps = {"train": 8, "validation": 2, "test": 2}[self._segment]
        return "\n".join(_SYNTHETIC_SENTENCES * reps)

    def _load(self):
        content = self._content()
        self._counter = text.utils.count_tokens_from_str(content)
        if self._vocab is None:
            self._vocab = text.vocab.Vocabulary(
                counter=self._counter, reserved_tokens=[EOS_TOKEN])
        lines = [ln.strip().split() for ln in content.splitlines()]
        tokens = []
        for ln in lines:
            if ln:
                tokens.extend(ln)
                tokens.append(EOS_TOKEN)
        raw = self._vocab.to_indices(tokens)
        data = onp.array(raw[:-1], dtype="int32")
        label = onp.array(raw[1:], dtype="int32")
        n = (len(data) // self._seq_len) * self._seq_len
        self._data = nd.array(data[:n]).reshape((-1, self._seq_len))
        self._label = nd.array(label[:n]).reshape((-1, self._seq_len))

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """WikiText-2 word-level LM dataset (reference text.py:104).
    Place the official ``wiki.{train,valid,test}.tokens`` under
    ``root`` to use real data; otherwise a synthetic corpus loads."""

    def __init__(self, root=os.path.join(data_dir(), "datasets",
                                         "wikitext-2"),
                 segment="train", vocab=None, seq_len=35):
        super().__init__(root, "wikitext-2", segment, vocab, seq_len)


class WikiText103(_WikiText):
    """WikiText-103 word-level LM dataset (reference text.py:142)."""

    def __init__(self, root=os.path.join(data_dir(), "datasets",
                                         "wikitext-103"),
                 segment="train", vocab=None, seq_len=35):
        super().__init__(root, "wikitext-103", segment, vocab, seq_len)
