"""Contributed samplers (reference:
python/mxnet/gluon/contrib/data/sampler.py:25). IntervalSampler lives
with the core samplers here; this module keeps the reference's import
path ``gluon.contrib.data.IntervalSampler`` working."""
from ...data.sampler import IntervalSampler

__all__ = ["IntervalSampler"]
