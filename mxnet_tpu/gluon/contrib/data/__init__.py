"""Contributed datasets + samplers
(reference: python/mxnet/gluon/contrib/data/)."""
from . import text
from .sampler import IntervalSampler
from .text import WikiText2, WikiText103

__all__ = ["IntervalSampler", "WikiText2", "WikiText103", "text"]
