"""Contributed convolutional layers
(reference: python/mxnet/gluon/contrib/cnn/)."""
from .conv_layers import DeformableConvolution, ModulatedDeformableConvolution

__all__ = ["DeformableConvolution", "ModulatedDeformableConvolution"]
