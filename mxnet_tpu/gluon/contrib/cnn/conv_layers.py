"""Deformable convolution Blocks (DCN v1/v2).

Reference analog: python/mxnet/gluon/contrib/cnn/conv_layers.py
(:30 DeformableConvolution, :224 ModulatedDeformableConvolution).
Each Block owns BOTH convolutions of the construct — the plain offset
(and, for v2, mask) generator and the deformable conv itself — exactly
as the reference does; the offset conv initializes to zeros so training
starts at a regular sampling grid. The underlying deformable sampling
op is ndarray/vision_ops.py's pure-XLA grid-sample + einsum kernel.
"""
from ....base import MXNetError
from .... import ndarray as nd
from ...block import HybridBlock
from ...parameter import Parameter

__all__ = ["DeformableConvolution", "ModulatedDeformableConvolution"]


def _tup2(v):
    return (v, v) if isinstance(v, (int, float)) else tuple(v)


class _DeformableBase(HybridBlock):
    _modulated = False

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        if layout != "NCHW":
            raise MXNetError("only NCHW layout is supported")
        kernel_size = _tup2(kernel_size)
        strides = _tup2(strides)
        padding = _tup2(padding)
        dilation = _tup2(dilation)
        self._channels = channels
        self._kernel = kernel_size
        self._strides = strides
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._num_deformable_group = num_deformable_group
        self._activation = activation
        kh, kw = kernel_size
        # v1: (dy,dx) per tap; v2 appends one modulation channel per tap
        per_tap = 3 if self._modulated else 2
        self._offset_channels = per_tap * kh * kw * num_deformable_group
        self._mask_split = 2 * kh * kw * num_deformable_group

        self.offset_weight = Parameter(
            "offset_weight",
            shape=(self._offset_channels,
                   in_channels // groups if in_channels else 0, kh, kw),
            init=offset_weight_initializer)
        self.offset_bias = Parameter(
            "offset_bias", shape=(self._offset_channels,),
            init=offset_bias_initializer) if offset_use_bias else None
        self.deformable_conv_weight = Parameter(
            "deformable_conv_weight",
            shape=(channels,
                   in_channels // groups if in_channels else 0, kh, kw),
            init=weight_initializer)
        self.deformable_conv_bias = Parameter(
            "deformable_conv_bias", shape=(channels,),
            init=bias_initializer) if use_bias else None

    def _infer(self, x):
        if self.deformable_conv_weight._data is None:
            in_ch = x.shape[1]
            kh, kw = self._kernel
            g = self._groups
            self.offset_weight.shape = (self._offset_channels,
                                        in_ch // g, kh, kw)
            self.deformable_conv_weight.shape = (self._channels,
                                                 in_ch // g, kh, kw)
            for p in (self.offset_weight, self.offset_bias,
                      self.deformable_conv_weight,
                      self.deformable_conv_bias):
                if p is not None and p._deferred_init_args is not None:
                    p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        ob = None if self.offset_bias is None else self.offset_bias.data()
        offset = nd.Convolution(
            x, self.offset_weight.data(), ob, kernel=self._kernel,
            stride=self._strides, dilate=self._dilation,
            pad=self._padding, num_filter=self._offset_channels,
            num_group=self._groups, no_bias=ob is None)
        db = None if self.deformable_conv_bias is None \
            else self.deformable_conv_bias.data()
        if self._modulated:
            off = nd.slice_axis(offset, axis=1, begin=0,
                                end=self._mask_split)
            mask = nd.slice_axis(offset, axis=1, begin=self._mask_split,
                                 end=None)
            mask = nd.sigmoid(mask) * 2
            out = nd.contrib.ModulatedDeformableConvolution(
                x, off, mask, self.deformable_conv_weight.data(), db,
                kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding,
                num_filter=self._channels, num_group=self._groups,
                num_deformable_group=self._num_deformable_group,
                no_bias=db is None)
        else:
            out = nd.contrib.DeformableConvolution(
                x, offset, self.deformable_conv_weight.data(), db,
                kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding,
                num_filter=self._channels, num_group=self._groups,
                num_deformable_group=self._num_deformable_group,
                no_bias=db is None)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        shape = self.deformable_conv_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape and shape[1] else None, self._channels)
        return (f"{type(self).__name__}({mapping}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class DeformableConvolution(_DeformableBase):
    """DCNv1 Block (reference conv_layers.py:30): a zero-initialized
    plain conv produces per-tap sampling offsets, the deformable conv
    consumes them."""
    _modulated = False


class ModulatedDeformableConvolution(_DeformableBase):
    """DCNv2 Block (reference conv_layers.py:224): the generator conv
    additionally emits per-tap modulation logits, mapped through
    ``2*sigmoid`` (reference :381) before modulating the samples."""
    _modulated = True
