"""Estimator API (reference: python/mxnet/gluon/contrib/estimator/)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import (EventHandler, TrainBegin, TrainEnd, EpochBegin,  # noqa: F401
                            EpochEnd, BatchBegin, BatchEnd, StoppingHandler,
                            CheckpointHandler, EarlyStoppingHandler,
                            LoggingHandler, MetricHandler, ValidationHandler)
