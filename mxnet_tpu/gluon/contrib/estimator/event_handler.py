"""Estimator event handlers (reference: python/mxnet/gluon/contrib/
estimator/event_handler.py — CheckpointHandler, EarlyStoppingHandler,
LoggingHandler, etc. hooked at train/epoch/batch boundaries).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as onp

__all__ = ["EventHandler", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch/max_batch (reference StoppingHandler)."""

    def __init__(self, max_epoch: Optional[int] = None,
                 max_batch: Optional[int] = None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Resets/updates train metrics (reference MetricHandler)."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, pred=None, label=None, loss=None,
                  **kwargs):
        for m in self.metrics:
            if "loss" in m.name.lower():
                m.update(None, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs validation every ``epoch_period`` epochs (reference
    ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period: int = 1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.current_epoch = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Logs metrics per epoch (and optionally per N batches)."""

    def __init__(self, log_interval: str = "epoch", metrics=None,
                 logger=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.logger = logger or logging.getLogger("mxnet_tpu.estimator")
        self.batch_index = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training end; total time %.1fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        msg = ", ".join(f"{m.name}={m.get()[1]:.4f}" for m in self.metrics)
        self.logger.info("Epoch done (%.1fs) %s",
                         time.time() - self.epoch_start, msg)

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = ", ".join(f"{m.name}={m.get()[1]:.4f}"
                            for m in self.metrics)
            self.logger.info("Batch %d %s", self.batch_index, msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Saves model (and best-model) checkpoints (reference
    CheckpointHandler: model_dir/model_prefix, monitor + mode).

    The ``.params`` files keep the reference naming but now land via the
    crash-safe staged write (``nd.save`` is atomic). With
    ``save_trainer_states=True`` the FULL train state (params + fused/
    ZeRO optimizer state + counters + RNG) additionally goes through
    ``mx.checkpoint.TrainCheckpointManager`` under
    ``<model_dir>/<prefix>-ckpt/`` — atomic, checksummed, pruned to
    ``keep_last`` — and ``resume_from_checkpoint=True`` restores the
    newest valid one at ``train_begin``."""

    def __init__(self, model_dir: str, model_prefix: str = "model",
                 monitor=None, mode: str = "min", save_best: bool = False,
                 epoch_period: int = 1, save_trainer_states: bool = True,
                 keep_last: int = 3, resume_from_checkpoint: bool = False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.current_epoch = 0
        if mode not in ("min", "max"):
            raise ValueError("mode must be min/max")
        self.mode = mode
        self.best = float("inf") if mode == "min" else -float("inf")
        self.save_trainer_states = save_trainer_states
        self.keep_last = keep_last
        self.resume_from_checkpoint = resume_from_checkpoint
        self._manager = None

    def _get_manager(self):
        if self._manager is None:
            from ....checkpoint.manager import TrainCheckpointManager
            self._manager = TrainCheckpointManager(
                os.path.join(self.model_dir,
                             f"{self.model_prefix}-ckpt"),
                keep_last=self.keep_last)
        return self._manager

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        if self.resume_from_checkpoint and self.save_trainer_states:
            meta = self._get_manager().restore_latest(
                trainer=getattr(estimator, "trainer", None),
                net=getattr(estimator, "net", None), strict=False)
            if meta is not None:
                self.current_epoch = int(meta.get("step", 0))

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period:
            return
        prefix = os.path.join(self.model_dir, self.model_prefix)
        estimator.net.save_parameters(
            f"{prefix}-epoch{self.current_epoch}.params")
        trainer = getattr(estimator, "trainer", None)
        if self.save_trainer_states and trainer is not None:
            # the atomic path handles fused/ZeRO state that the old
            # Trainer.save_states pickle cannot see
            self._get_manager().save(self.current_epoch, trainer=trainer,
                                     net=estimator.net, block=True)
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            better = val < self.best if self.mode == "min" else val > self.best
            if better:
                self.best = val
                estimator.net.save_parameters(f"{prefix}-best.params")


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stops when the monitored metric stops improving (reference
    EarlyStoppingHandler: monitor/min_delta/patience/mode)."""

    def __init__(self, monitor, min_delta: float = 0.0, patience: int = 0,
                 mode: str = "min"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        if mode not in ("min", "max"):
            raise ValueError("mode must be min/max")
        self.mode = mode
        self.best = float("inf") if mode == "min" else -float("inf")
        self.wait = 0
        self.stop_training = False
        self.stopped_epoch = 0
        self.current_epoch = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        _, val = self.monitor.get()
        if onp.isnan(val):
            return self.stop_training
        improved = (val < self.best - self.min_delta) if self.mode == "min" \
            else (val > self.best + self.min_delta)
        if improved:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
        return self.stop_training
