"""Estimator: Keras-like fit loop (reference: python/mxnet/gluon/contrib/
estimator/estimator.py — Estimator.fit with event handlers dispatched at
train/epoch/batch boundaries).
"""
from __future__ import annotations

from typing import Optional

from .... import autograd, metric as metric_mod
from ....base import MXNetError
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd)

__all__ = ["Estimator"]


class Estimator:
    """Trains a Gluon net over a DataLoader with pluggable handlers."""

    def __init__(self, net, loss, train_metrics=None, trainer: Optional[Trainer] = None,
                 context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics if train_metrics is not None else \
            [metric_mod.Accuracy()]
        if not isinstance(self.train_metrics, (list, tuple)):
            self.train_metrics = [self.train_metrics]
        self.train_metrics = list(self.train_metrics)
        self.train_loss_metric = metric_mod.Loss("train_loss")
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.01})

    def _dispatch(self, handlers, event, *args, **kwargs):
        stop = False
        for h in handlers:
            r = getattr(h, event)(self, *args, **kwargs)
            stop = stop or bool(r)
        return stop

    def evaluate(self, val_data, val_metrics=None):
        """One pass over val_data updating val_metrics."""
        metrics = val_metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            x, y = batch[0], batch[1]
            pred = self.net(x)
            for m in metrics:
                if "loss" in m.name.lower():
                    m.update(None, self.loss(pred, y))
                else:
                    m.update(y, pred)
        return metrics

    def fit(self, train_data, val_data=None, epochs: Optional[int] = None,
            event_handlers=None, batches: Optional[int] = None):
        if epochs is None and batches is None:
            raise MXNetError("fit requires epochs or batches")
        stopper = StoppingHandler(max_epoch=epochs, max_batch=batches)
        handlers = [stopper,
                    MetricHandler([self.train_loss_metric] +
                                  self.train_metrics)]
        if event_handlers:
            handlers.extend(event_handlers)
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=[self.train_loss_metric] + self.train_metrics))

        tb = [h for h in handlers if isinstance(h, TrainBegin)]
        te = [h for h in handlers if isinstance(h, TrainEnd)]
        eb = [h for h in handlers if isinstance(h, EpochBegin)]
        ee = [h for h in handlers if isinstance(h, EpochEnd)]
        bb = [h for h in handlers if isinstance(h, BatchBegin)]
        be = [h for h in handlers if isinstance(h, BatchEnd)]

        self._dispatch(tb, "train_begin")
        while not stopper.stop_training:
            self._dispatch(eb, "epoch_begin")
            for batch in train_data:
                x, y = batch[0], batch[1]
                self._dispatch(bb, "batch_begin")
                with autograd.record():
                    pred = self.net(x)
                    loss = self.loss(pred, y)
                loss.backward()
                bs = x.shape[0]
                self.trainer.step(bs)
                if self._dispatch(be, "batch_end", pred=pred, label=y,
                                  loss=loss):
                    break
            if self._dispatch(ee, "epoch_end"):
                break
        self._dispatch(te, "train_end")
        return self
