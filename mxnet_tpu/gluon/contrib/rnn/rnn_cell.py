"""Contrib recurrent cells (reference
python/mxnet/gluon/contrib/rnn/rnn_cell.py: VariationalDropoutCell,
LSTMPCell)."""
from __future__ import annotations

from ....ndarray import ops as F
from ...parameter import Parameter
from ...rnn.rnn_cell import RecurrentCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(RecurrentCell):
    """Variational (time-locked) dropout around a base cell
    (reference contrib VariationalDropoutCell; Gal & Ghahramani,
    arXiv:1512.05287): ONE dropout mask per sequence for inputs, for the
    first state channel, and for outputs — sampled at the first step and
    reused until ``reset()``. Step manually? call ``reset()`` between
    sequences, exactly like the reference."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size=batch_size, **kwargs)

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def forward(self, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(F.ones_like(states[0]),
                                              p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(F.ones_like(inputs),
                                              p=self.drop_inputs)
        if self.drop_states:
            states = list(states)
            # only h — always the first state channel (reference contract)
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask
        out, next_states = self.base_cell(inputs, states)
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(F.ones_like(out),
                                               p=self.drop_outputs)
        if self.drop_outputs:
            out = out * self.drop_outputs_mask
        return out, next_states

    def __repr__(self):
        return (f"{type(self).__name__}(p_out={self.drop_outputs}, "
                f"p_state={self.drop_states})")


class LSTMPCell(RecurrentCell):
    """LSTM with a hidden-state projection (reference contrib LSTMPCell;
    Sak et al. 2014): the (N, H) hidden is projected to (N, P) before
    recurring, shrinking the h2h matmul from H×H to 4H×P — the LSTMP
    trick that keeps big-H cells MXU-efficient. Gate order [i, f, g, o];
    states ``[r (N, P), c (N, H)]``; the projection has no bias."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer)
        self.h2r_weight = Parameter(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self._input_size == 0:
            self._input_size = inputs.shape[-1]
            self.i2h_weight.shape = (self.i2h_weight.shape[0],
                                     inputs.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.h2r_weight,
                  self.i2h_bias, self.h2h_bias):
            if p._data is None and p._deferred_init_args is not None:
                p._finish_deferred_init()
        r, c = states
        i2h = F.FullyConnected(inputs, self.i2h_weight.data(),
                               self.i2h_bias.data(),
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(r, self.h2h_weight.data(),
                               self.h2h_bias.data(),
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        c_new = F.sigmoid(f) * c + F.sigmoid(i) * F.tanh(g)
        hidden = F.sigmoid(o) * F.tanh(c_new)
        r_new = F.FullyConnected(hidden, self.h2r_weight.data(), None,
                                 num_hidden=self._projection_size,
                                 no_bias=True)
        return r_new, [r_new, c_new]
