"""Convolutional recurrent cells (reference
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py — 9 public classes).

TPU-native: each step is two XLA convolutions (i2h on the input, h2h on
the hidden state, both MXU-bound) plus fused gate arithmetic; unrolling
under hybridize/jit produces one compiled program per sequence length.
The h2h convolution is constrained to odd kernels with SAME padding
(dilate*(k-1)//2) exactly like the reference, so the state keeps its
spatial shape across steps.
"""
from __future__ import annotations

from ....base import MXNetError
from ....ndarray import ops as F
from ....ndarray.nn_ops import Convolution
from ...parameter import Parameter
from ...rnn.rnn_cell import RecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n, name):
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    if len(t) != n:
        raise MXNetError(f"{name} must be an int or length-{n} tuple, "
                         f"got {v!r}")
    return t


class _BaseConvRNNCell(RecurrentCell):
    """Shared conv/parameter plumbing for the nine cells."""

    _gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dims=2, conv_layout="NCHW", activation="tanh", **kwargs):
        super().__init__(**kwargs)
        if conv_layout != "NC" + "DHW"[3 - dims:]:
            raise MXNetError(
                f"only the channel-first layout is supported, got "
                f"{conv_layout!r} (XLA lays out MXU convs internally; the "
                "reference's layout knob is a cuDNN artifact)")
        self._dims = dims
        self._input_shape = tuple(input_shape)  # (C_in, *spatial)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims, "i2h_kernel")
        self._i2h_pad = _tup(i2h_pad, dims, "i2h_pad")
        self._i2h_dilate = _tup(i2h_dilate, dims, "i2h_dilate")
        self._h2h_kernel = _tup(h2h_kernel, dims, "h2h_kernel")
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise MXNetError(f"h2h_kernel must be odd (SAME padding keeps "
                             f"the state shape), got {self._h2h_kernel}")
        self._h2h_dilate = _tup(h2h_dilate, dims, "h2h_dilate")
        self._h2h_pad = tuple(d * (k - 1) // 2
                              for d, k in zip(self._h2h_dilate,
                                              self._h2h_kernel))
        c_in = self._input_shape[0]
        ng = self._gates * hidden_channels
        self.i2h_weight = Parameter(
            "i2h_weight", shape=(ng, c_in) + self._i2h_kernel,
            init=i2h_weight_initializer)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(ng, hidden_channels) + self._h2h_kernel,
            init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(ng,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(ng,),
                                  init=h2h_bias_initializer)

    @property
    def _state_spatial(self):
        """Spatial dims of the hidden state: the i2h conv output shape
        over input_shape (stride 1), same rule as the reference."""
        out = []
        for x, k, p, d in zip(self._input_shape[1:], self._i2h_kernel,
                              self._i2h_pad, self._i2h_dilate):
            out.append((x + 2 * p - d * (k - 1) - 1) + 1)
        return tuple(out)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial
        return [{"shape": shape, "__layout__": "NC" + "DHW"[3 - self._dims:]}]

    def _convs(self, x, h):
        i2h = Convolution(
            x, self.i2h_weight.data(), self.i2h_bias.data(),
            kernel=self._i2h_kernel, stride=(1,) * self._dims,
            dilate=self._i2h_dilate, pad=self._i2h_pad,
            num_filter=self._gates * self._hidden_channels)
        h2h = Convolution(
            h, self.h2h_weight.data(), self.h2h_bias.data(),
            kernel=self._h2h_kernel, stride=(1,) * self._dims,
            dilate=self._h2h_dilate, pad=self._h2h_pad,
            num_filter=self._gates * self._hidden_channels)
        return i2h, h2h

    def _act(self, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _gates = 1

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        out = self._act(i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    """Conv LSTM (Shi et al. 2015; gate order [i, f, g, o] like the
    reference)."""

    _gates = 4

    def state_info(self, batch_size=0):
        info = super().state_info(batch_size)[0]
        return [dict(info), dict(info)]

    def forward(self, inputs, states):
        h, c = states
        i2h, h2h = self._convs(inputs, h)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = self._act(g)
        o = F.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * self._act(c_new)
        return h_new, [h_new, c_new]


class _ConvGRUCell(_BaseConvRNNCell):
    _gates = 3

    def forward(self, inputs, states):
        h = states[0]
        i2h, h2h = self._convs(inputs, h)
        xr, xz, xn = F.split(i2h, num_outputs=3, axis=1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = self._act(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        return h_new, [h_new]


class _DimCell:
    """Mixin fixing dims/default layout for the public 1/2/3-D cells."""

    _dims = 2
    _layout = "NCHW"

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=None, activation="tanh", **kwargs):
        super().__init__(
            input_shape=input_shape, hidden_channels=hidden_channels,
            i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel, i2h_pad=i2h_pad,
            i2h_dilate=i2h_dilate, h2h_dilate=h2h_dilate,
            i2h_weight_initializer=i2h_weight_initializer,
            h2h_weight_initializer=h2h_weight_initializer,
            i2h_bias_initializer=i2h_bias_initializer,
            h2h_bias_initializer=h2h_bias_initializer,
            dims=self._dims,
            conv_layout=conv_layout if conv_layout is not None
            else self._layout,
            activation=activation, **kwargs)


class Conv1DRNNCell(_DimCell, _ConvRNNCell):
    """1D conv RNN cell (reference Conv1DRNNCell)."""
    _dims, _layout = 1, "NCW"


class Conv2DRNNCell(_DimCell, _ConvRNNCell):
    """2D conv RNN cell (reference Conv2DRNNCell)."""
    _dims, _layout = 2, "NCHW"


class Conv3DRNNCell(_DimCell, _ConvRNNCell):
    """3D conv RNN cell (reference Conv3DRNNCell)."""
    _dims, _layout = 3, "NCDHW"


class Conv1DLSTMCell(_DimCell, _ConvLSTMCell):
    """1D conv LSTM cell (reference Conv1DLSTMCell; Shi et al. 2015)."""
    _dims, _layout = 1, "NCW"


class Conv2DLSTMCell(_DimCell, _ConvLSTMCell):
    """2D conv LSTM cell (reference Conv2DLSTMCell; Shi et al. 2015)."""
    _dims, _layout = 2, "NCHW"


class Conv3DLSTMCell(_DimCell, _ConvLSTMCell):
    """3D conv LSTM cell (reference Conv3DLSTMCell; Shi et al. 2015)."""
    _dims, _layout = 3, "NCDHW"


class Conv1DGRUCell(_DimCell, _ConvGRUCell):
    """1D conv GRU cell (reference Conv1DGRUCell)."""
    _dims, _layout = 1, "NCW"


class Conv2DGRUCell(_DimCell, _ConvGRUCell):
    """2D conv GRU cell (reference Conv2DGRUCell)."""
    _dims, _layout = 2, "NCHW"


class Conv3DGRUCell(_DimCell, _ConvGRUCell):
    """3D conv GRU cell (reference Conv3DGRUCell)."""
    _dims, _layout = 3, "NCDHW"
