"""Contrib recurrent cells (reference python/mxnet/gluon/contrib/rnn/)."""
from .conv_rnn_cell import (Conv1DRNNCell, Conv2DRNNCell, Conv3DRNNCell,
                            Conv1DLSTMCell, Conv2DLSTMCell, Conv3DLSTMCell,
                            Conv1DGRUCell, Conv2DGRUCell, Conv3DGRUCell)
from .rnn_cell import VariationalDropoutCell, LSTMPCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "VariationalDropoutCell", "LSTMPCell"]
