"""gluon.contrib (reference: python/mxnet/gluon/contrib/)."""
from . import estimator  # noqa: F401
