"""gluon.contrib (reference: python/mxnet/gluon/contrib/)."""
from . import cnn  # noqa: F401
from . import data  # noqa: F401
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
