"""Contrib neural-network layers (reference
python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SyncBatchNorm, PixelShuffle1D,
                           PixelShuffle2D, PixelShuffle3D)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]
