"""Contrib layers (reference python/mxnet/gluon/contrib/nn/basic_layers.py).

Concurrent/HybridConcurrent/Identity/SyncBatchNorm already exist in core
``gluon.nn`` under their 2.0 names (Concatenate et al., the rename the
reference performed for 2.0); contrib re-exports them under the contrib
names so reference-era code imports unchanged. PixelShuffle1D/2D/3D are
implemented here: on TPU they are pure reshape/transpose programs that XLA
fuses into the surrounding convolutions (no data movement beyond the final
layout change), the idiomatic form of the reference's sub-pixel
convolution upsampling (arXiv:1609.05158).
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ...nn.basic_layers import (Concatenate, HybridConcatenate, Identity,
                                SyncBatchNorm, Embedding)
from ....ndarray import ops as F

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Concatenate):
    """Run children on the same input and concat outputs along ``axis``
    (reference contrib Concurrent == 2.0 nn.Concatenate)."""


class HybridConcurrent(HybridConcatenate):
    """Hybridizable Concurrent (reference contrib HybridConcurrent)."""


class SparseEmbedding(Embedding):
    """Embedding with row_sparse gradients (reference contrib
    SparseEmbedding, deprecated upstream in favor of
    ``nn.Embedding(sparse_grad=True)`` — same here)."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         sparse_grad=True, **kwargs)


def _factors(factor, n):
    try:
        return (int(factor),) * n
    except TypeError:
        f = tuple(int(v) for v in factor)
        if len(f) != n:
            raise MXNetError(f"factor must be an int or {n}-tuple, got "
                             f"{factor!r}")
        return f


class PixelShuffle1D(HybridBlock):
    """(N, f*C, W) -> (N, C, W*f): channel groups of f become W-blocks
    (reference contrib PixelShuffle1D)."""

    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def forward(self, x):
        f = self._factor
        n, fc, w = x.shape
        c = fc // f
        x = F.reshape(x, (n, c, f, w))          # channel index = c*f + j
        x = F.transpose(x, axes=(0, 1, 3, 2))   # (N, C, W, f)
        return F.reshape(x, (n, c, w * f))

    def __repr__(self):
        return f"{type(self).__name__}({self._factor})"


class PixelShuffle2D(HybridBlock):
    """(N, f1*f2*C, H, W) -> (N, C, H*f1, W*f2) (reference contrib
    PixelShuffle2D)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = _factors(factor, 2)

    def forward(self, x):
        f1, f2 = self._factors
        n, c_in, h, w = x.shape
        c = c_in // (f1 * f2)
        x = F.reshape(x, (n, c, f1, f2, h, w))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))  # (N, C, H, f1, W, f2)
        return F.reshape(x, (n, c, h * f1, w * f2))

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"


class PixelShuffle3D(HybridBlock):
    """(N, f1*f2*f3*C, D, H, W) -> (N, C, D*f1, H*f2, W*f3) (reference
    contrib PixelShuffle3D; one transpose — XLA handles 7-D permutes, no
    need for the reference's swapaxes chain that works around a 6-D
    transpose limit)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = _factors(factor, 3)

    def forward(self, x):
        f1, f2, f3 = self._factors
        n, c_in, d, h, w = x.shape
        c = c_in // (f1 * f2 * f3)
        x = F.reshape(x, (n, c, f1, f2, f3, d, h, w))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, (n, c, d * f1, h * f2, w * f3))

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"
