"""Gluon: the imperative/hybrid user API (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .utils import split_and_load, split_data, clip_global_norm

import importlib as _importlib

for _mod in ["trainer", "data", "rnn", "model_zoo", "contrib", "probability"]:
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ImportError:
        pass

try:
    from .trainer import Trainer  # noqa: F401
    from .fused_step import TrainLoop, CompiledTrainStep  # noqa: F401
    from .pipeline import PipelineTrainer  # noqa: F401
except ImportError:
    pass

try:
    from .gqa_decoder import GQADecoder  # noqa: F401
except ImportError:
    pass

from .. import metric  # parity: mx.gluon.metric mirrors reference layout
