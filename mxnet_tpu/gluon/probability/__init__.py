"""gluon.probability (reference: python/mxnet/gluon/probability/).

Distributions, a KL registry, transformations, and StochasticBlock.
Sampling uses the framework RNG stream (functional JAX keys under the
hood); log_prob/entropy/kl are pure ops XLA fuses into surrounding
computation.
"""
from .distributions import *  # noqa: F401,F403
from .transformation import *  # noqa: F401,F403
from .block import StochasticBlock  # noqa: F401
from . import constraint  # noqa: F401
from . import distributions, transformation, block

__all__ = list(distributions.__all__) + list(transformation.__all__) \
    + ["StochasticBlock", "constraint"]
