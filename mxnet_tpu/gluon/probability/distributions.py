"""Distribution zoo (reference: python/mxnet/gluon/probability/distributions/
— ~20 distribution classes with sample/log_prob/entropy + a KL registry).

Every density/entropy is a pure jnp computation flowing through the op
invoke funnel (differentiable on the tape, fusable by XLA); sampling draws
from the framework's stateless key chain (ndarray/random.py next_key), so
``mx.random.seed`` reproduces sample paths.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray.random import next_key
from ...ops.registry import invoke_raw

__all__ = ["Distribution", "Normal", "LogNormal", "HalfNormal", "Laplace",
           "Cauchy", "HalfCauchy", "Gumbel", "Uniform", "Exponential",
           "Gamma", "Beta", "Chi2", "StudentT", "Weibull", "Pareto",
           "Bernoulli", "Geometric", "Poisson", "Categorical",
           "OneHotCategorical", "Dirichlet", "MultivariateNormal",
           "Binomial", "NegativeBinomial", "Multinomial", "FisherSnedecor",
           "Independent", "kl_divergence", "register_kl"]

_EULER = 0.5772156649015329


def _data(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x, jnp.float32)


def _op(name, fn, inputs):
    return invoke_raw(name, fn, [x if isinstance(x, NDArray)
                                 else NDArray(jnp.asarray(x, jnp.float32))
                                 for x in inputs])


def _sum_rightmost(x, n):
    """Sum the trailing n axes (event-dim reduction; shared by
    Independent and the transformation module)."""
    return jnp.sum(x, axis=tuple(range(x.ndim - n, x.ndim))) if n else x


class Distribution:
    """Base distribution (reference distribution.py Distribution)."""

    has_grad = True
    support = None
    event_dim = 0  # trailing dims that form one event (reference
    # Distribution.event_dim; 1 for simplex/vector-valued laws)

    def __init__(self, **params):
        # keep the caller's NDArray objects: their tape identity is what
        # lets gradients flow back to distribution parameters
        self._nd_params = {
            k: v if isinstance(v, NDArray)
            else NDArray(jnp.asarray(v, jnp.float32))
            for k, v in params.items()}
        self._params = {k: v._data for k, v in self._nd_params.items()}
        for k, v in self._nd_params.items():
            setattr(self, k, v)

    def _p(self, name):
        return self._params[name]

    def _sample_shape(self, size):
        base = jnp.broadcast_shapes(*[p.shape for p in
                                      self._params.values()]) \
            if self._params else ()
        if size is None:
            return base
        if isinstance(size, int):
            size = (size,)
        return tuple(size) + base

    # -- interface --------------------------------------------------------
    def sample(self, size=None) -> NDArray:
        key = next_key()
        shape = self._sample_shape(size)
        fn = lambda *ps: self._sample_impl(key, shape, *ps)
        return _op(f"{type(self).__name__}_sample", fn,
                   list(self._nd_params.values()))

    def sample_n(self, size=None):
        return self.sample(size)

    def log_prob(self, value) -> NDArray:
        fn = lambda v, *ps: self._log_prob_impl(v, *ps)
        return _op(f"{type(self).__name__}_log_prob", fn,
                   [value] + list(self._nd_params.values()))

    def prob(self, value) -> NDArray:
        lp = self.log_prob(value)
        return _op("exp", jnp.exp, [lp])

    def entropy(self) -> NDArray:
        fn = lambda *ps: self._entropy_impl(*ps)
        return _op(f"{type(self).__name__}_entropy", fn,
                   list(self._nd_params.values()))

    @property
    def mean(self) -> NDArray:
        return NDArray(self._mean_impl(*self._params.values()))

    @property
    def variance(self) -> NDArray:
        return NDArray(self._variance_impl(*self._params.values()))

    def cdf(self, value) -> NDArray:
        fn = lambda v, *ps: self._cdf_impl(v, *ps)
        return _op(f"{type(self).__name__}_cdf", fn,
                   [value] + list(self._nd_params.values()))

    def icdf(self, value) -> NDArray:
        fn = lambda v, *ps: self._icdf_impl(v, *ps)
        return _op(f"{type(self).__name__}_icdf", fn,
                   [value] + list(self._nd_params.values()))

    # -- per-distribution hooks ------------------------------------------
    def _sample_impl(self, key, shape, *params):
        raise NotImplementedError

    def _log_prob_impl(self, value, *params):
        raise NotImplementedError

    def _entropy_impl(self, *params):
        raise MXNetError(f"{type(self).__name__} has no closed-form entropy")

    def _mean_impl(self, *params):
        raise NotImplementedError

    def _variance_impl(self, *params):
        raise NotImplementedError

    def _cdf_impl(self, value, *params):
        raise MXNetError(f"{type(self).__name__} has no closed-form cdf")

    def _icdf_impl(self, value, *params):
        raise MXNetError(f"{type(self).__name__} has no closed-form icdf")


class Normal(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def _sample_impl(self, key, shape, loc, scale):
        return loc + scale * jax.random.normal(key, shape)

    def _log_prob_impl(self, v, loc, scale):
        z = (v - loc) / scale
        return -0.5 * z * z - jnp.log(scale) - 0.5 * math.log(2 * math.pi)

    def _entropy_impl(self, loc, scale):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale) \
            + jnp.zeros_like(loc)

    def _mean_impl(self, loc, scale):
        return jnp.broadcast_to(loc, jnp.broadcast_shapes(loc.shape,
                                                          scale.shape))

    def _variance_impl(self, loc, scale):
        return jnp.broadcast_to(scale * scale,
                                jnp.broadcast_shapes(loc.shape, scale.shape))

    def _cdf_impl(self, v, loc, scale):
        return 0.5 * (1 + lax.erf((v - loc) / (scale * math.sqrt(2.0))))

    def _icdf_impl(self, v, loc, scale):
        return loc + scale * math.sqrt(2.0) * lax.erf_inv(2 * v - 1)


class LogNormal(Normal):
    def _sample_impl(self, key, shape, loc, scale):
        return jnp.exp(super()._sample_impl(key, shape, loc, scale))

    def _log_prob_impl(self, v, loc, scale):
        return super()._log_prob_impl(jnp.log(v), loc, scale) - jnp.log(v)

    def _mean_impl(self, loc, scale):
        return jnp.exp(loc + scale * scale / 2)

    def _variance_impl(self, loc, scale):
        s2 = scale * scale
        return (jnp.exp(s2) - 1) * jnp.exp(2 * loc + s2)

    def _entropy_impl(self, loc, scale):
        return loc + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)


class HalfNormal(Distribution):
    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def _sample_impl(self, key, shape, scale):
        return jnp.abs(scale * jax.random.normal(key, shape))

    def _log_prob_impl(self, v, scale):
        z = v / scale
        return math.log(2.) - 0.5 * z * z - jnp.log(scale) \
            - 0.5 * math.log(2 * math.pi)

    def _mean_impl(self, scale):
        return scale * math.sqrt(2 / math.pi)

    def _variance_impl(self, scale):
        return scale * scale * (1 - 2 / math.pi)


class Laplace(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def _sample_impl(self, key, shape, loc, scale):
        return loc + scale * jax.random.laplace(key, shape)

    def _log_prob_impl(self, v, loc, scale):
        return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)

    def _entropy_impl(self, loc, scale):
        return 1 + jnp.log(2 * scale) + jnp.zeros_like(loc)

    def _mean_impl(self, loc, scale):
        return jnp.broadcast_to(loc, jnp.broadcast_shapes(loc.shape,
                                                          scale.shape))

    def _variance_impl(self, loc, scale):
        return 2 * scale * scale + jnp.zeros_like(loc)


class Cauchy(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def _sample_impl(self, key, shape, loc, scale):
        return loc + scale * jax.random.cauchy(key, shape)

    def _log_prob_impl(self, v, loc, scale):
        z = (v - loc) / scale
        return -jnp.log1p(z * z) - jnp.log(math.pi * 1.0) - jnp.log(scale)

    def _entropy_impl(self, loc, scale):
        return jnp.log(4 * math.pi * scale) + jnp.zeros_like(loc)


class HalfCauchy(Distribution):
    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def _sample_impl(self, key, shape, scale):
        return jnp.abs(scale * jax.random.cauchy(key, shape))

    def _log_prob_impl(self, v, scale):
        z = v / scale
        return math.log(2 / math.pi) - jnp.log1p(z * z) - jnp.log(scale)


class Gumbel(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def _sample_impl(self, key, shape, loc, scale):
        return loc + scale * jax.random.gumbel(key, shape)

    def _log_prob_impl(self, v, loc, scale):
        z = (v - loc) / scale
        return -(z + jnp.exp(-z)) - jnp.log(scale)

    def _entropy_impl(self, loc, scale):
        return jnp.log(scale) + 1 + _EULER + jnp.zeros_like(loc)

    def _mean_impl(self, loc, scale):
        return loc + scale * _EULER

    def _variance_impl(self, loc, scale):
        return (math.pi ** 2 / 6) * scale * scale + jnp.zeros_like(loc)


class Uniform(Distribution):
    def __init__(self, low=0.0, high=1.0):
        super().__init__(low=low, high=high)

    def _sample_impl(self, key, shape, low, high):
        return jax.random.uniform(key, shape, minval=0., maxval=1.) \
            * (high - low) + low

    def _log_prob_impl(self, v, low, high):
        inside = (v >= low) & (v <= high)
        return jnp.where(inside, -jnp.log(high - low), -jnp.inf)

    def _entropy_impl(self, low, high):
        return jnp.log(high - low)

    def _mean_impl(self, low, high):
        return (low + high) / 2

    def _variance_impl(self, low, high):
        return (high - low) ** 2 / 12

    def _cdf_impl(self, v, low, high):
        return jnp.clip((v - low) / (high - low), 0.0, 1.0)

    def _icdf_impl(self, v, low, high):
        return low + v * (high - low)


class Exponential(Distribution):
    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def _sample_impl(self, key, shape, scale):
        return scale * jax.random.exponential(key, shape)

    def _log_prob_impl(self, v, scale):
        return -v / scale - jnp.log(scale)

    def _entropy_impl(self, scale):
        return 1 + jnp.log(scale)

    def _mean_impl(self, scale):
        return scale

    def _variance_impl(self, scale):
        return scale * scale

    def _cdf_impl(self, v, scale):
        return -jnp.expm1(-v / scale)

    def _icdf_impl(self, v, scale):
        return -scale * jnp.log1p(-v)


class Gamma(Distribution):
    def __init__(self, shape=1.0, scale=1.0):
        super().__init__(alpha=shape, scale=scale)

    def _sample_impl(self, key, shape, alpha, scale):
        return scale * jax.random.gamma(key, alpha, shape)

    def _log_prob_impl(self, v, alpha, scale):
        return (alpha - 1) * jnp.log(v) - v / scale \
            - lax.lgamma(alpha) - alpha * jnp.log(scale)

    def _mean_impl(self, alpha, scale):
        return alpha * scale

    def _variance_impl(self, alpha, scale):
        return alpha * scale * scale


class Beta(Distribution):
    def __init__(self, alpha=1.0, beta=1.0):
        super().__init__(alpha=alpha, beta=beta)

    def _sample_impl(self, key, shape, alpha, beta):
        return jax.random.beta(key, alpha, beta, shape)

    def _log_prob_impl(self, v, alpha, beta):
        lbeta = lax.lgamma(alpha) + lax.lgamma(beta) - lax.lgamma(alpha + beta)
        return (alpha - 1) * jnp.log(v) + (beta - 1) * jnp.log1p(-v) - lbeta

    def _mean_impl(self, alpha, beta):
        return alpha / (alpha + beta)

    def _variance_impl(self, alpha, beta):
        t = alpha + beta
        return alpha * beta / (t * t * (t + 1))


class Chi2(Gamma):
    def __init__(self, df):
        Distribution.__init__(self, alpha=_data(df) / 2,
                              scale=jnp.full_like(_data(df), 2.0))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        super().__init__(df=df, loc=loc, scale=scale)

    def _sample_impl(self, key, shape, df, loc, scale):
        return loc + scale * jax.random.t(key, df, shape)

    def _log_prob_impl(self, v, df, loc, scale):
        z = (v - loc) / scale
        return lax.lgamma((df + 1) / 2) - lax.lgamma(df / 2) \
            - 0.5 * jnp.log(df * math.pi) - jnp.log(scale) \
            - (df + 1) / 2 * jnp.log1p(z * z / df)


class Weibull(Distribution):
    def __init__(self, concentration, scale=1.0):
        super().__init__(k=concentration, scale=scale)

    def _sample_impl(self, key, shape, k, scale):
        u = jax.random.uniform(key, shape, minval=1e-7, maxval=1.0)
        return scale * (-jnp.log(u)) ** (1 / k)

    def _log_prob_impl(self, v, k, scale):
        z = v / scale
        return jnp.log(k / scale) + (k - 1) * jnp.log(z) - z ** k

    def _mean_impl(self, k, scale):
        return scale * jnp.exp(lax.lgamma(1 + 1 / k))


class Pareto(Distribution):
    def __init__(self, alpha, scale=1.0):
        super().__init__(alpha=alpha, scale=scale)

    def _sample_impl(self, key, shape, alpha, scale):
        u = jax.random.uniform(key, shape, minval=1e-7, maxval=1.0)
        return scale * u ** (-1 / alpha)

    def _log_prob_impl(self, v, alpha, scale):
        valid = v >= scale
        lp = jnp.log(alpha) + alpha * jnp.log(scale) - (alpha + 1) * jnp.log(v)
        return jnp.where(valid, lp, -jnp.inf)


class Bernoulli(Distribution):
    def __init__(self, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("Bernoulli takes exactly one of prob/logit")
        if prob is None:
            prob = jax.nn.sigmoid(_data(logit))
        super().__init__(prob=prob)

    def _sample_impl(self, key, shape, prob):
        return jax.random.bernoulli(key, prob, shape).astype(jnp.float32)

    def _log_prob_impl(self, v, prob):
        eps = 1e-7
        p = jnp.clip(prob, eps, 1 - eps)
        return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

    def _entropy_impl(self, prob):
        eps = 1e-7
        p = jnp.clip(prob, eps, 1 - eps)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

    def _mean_impl(self, prob):
        return prob

    def _variance_impl(self, prob):
        return prob * (1 - prob)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k ∈ {0,1,...}."""

    def __init__(self, prob):
        super().__init__(prob=prob)

    def _sample_impl(self, key, shape, prob):
        u = jax.random.uniform(key, shape, minval=1e-7, maxval=1.0)
        return jnp.floor(jnp.log(u) / jnp.log1p(-prob))

    def _log_prob_impl(self, v, prob):
        return v * jnp.log1p(-prob) + jnp.log(prob)

    def _mean_impl(self, prob):
        return (1 - prob) / prob

    def _variance_impl(self, prob):
        return (1 - prob) / (prob * prob)


class Poisson(Distribution):
    def __init__(self, rate):
        super().__init__(rate=rate)

    def _sample_impl(self, key, shape, rate):
        return jax.random.poisson(key, rate, shape).astype(jnp.float32)

    def _log_prob_impl(self, v, rate):
        return v * jnp.log(rate) - rate - lax.lgamma(v + 1)

    def _mean_impl(self, rate):
        return rate

    def _variance_impl(self, rate):
        return rate


class Categorical(Distribution):
    """Integer-class distribution over the last axis of prob/logit."""

    def __init__(self, prob=None, logit=None, num_events=None):
        if (prob is None) == (logit is None):
            raise MXNetError("Categorical takes exactly one of prob/logit")
        logit = jnp.log(jnp.clip(_data(prob), 1e-30)) if logit is None \
            else _data(logit)
        super().__init__(logit=logit)
        self.num_events = num_events or logit.shape[-1]

    def _sample_shape(self, size):
        base = self._p("logit").shape[:-1]
        if size is None:
            return base
        size = (size,) if isinstance(size, int) else tuple(size)
        return size + base

    def _sample_impl(self, key, shape, logit):
        return jax.random.categorical(key, logit, axis=-1,
                                      shape=shape).astype(jnp.float32)

    def _log_prob_impl(self, v, logit):
        logp = jax.nn.log_softmax(logit, axis=-1)
        idx = v.astype(jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(logp, v.shape + (logp.shape[-1],)),
            idx[..., None], axis=-1)[..., 0]

    def _entropy_impl(self, logit):
        logp = jax.nn.log_softmax(logit, axis=-1)
        return -(jnp.exp(logp) * logp).sum(-1)

    @property
    def prob(self):
        return NDArray(jax.nn.softmax(self._p("logit"), axis=-1))


class OneHotCategorical(Categorical):
    event_dim = 1
    def _sample_impl(self, key, shape, logit):
        idx = jax.random.categorical(key, logit, axis=-1, shape=shape)
        return jax.nn.one_hot(idx, logit.shape[-1])

    def _sample_shape(self, size):
        return super()._sample_shape(size)

    def _log_prob_impl(self, v, logit):
        logp = jax.nn.log_softmax(logit, axis=-1)
        return (v * logp).sum(-1)


class Dirichlet(Distribution):
    event_dim = 1
    def __init__(self, alpha):
        super().__init__(alpha=alpha)

    def _sample_shape(self, size):
        base = self._p("alpha").shape
        if size is None:
            return base
        size = (size,) if isinstance(size, int) else tuple(size)
        return size + base

    def _sample_impl(self, key, shape, alpha):
        g = jax.random.gamma(key, jnp.broadcast_to(alpha, shape))
        return g / g.sum(-1, keepdims=True)

    def _log_prob_impl(self, v, alpha):
        lnorm = lax.lgamma(alpha).sum(-1) - lax.lgamma(alpha.sum(-1))
        return ((alpha - 1) * jnp.log(v)).sum(-1) - lnorm

    def _mean_impl(self, alpha):
        return alpha / alpha.sum(-1, keepdims=True)


class MultivariateNormal(Distribution):
    event_dim = 1
    """MVN parameterized by loc and covariance (or scale_tril)."""

    def __init__(self, loc, cov=None, scale_tril=None):
        if (cov is None) == (scale_tril is None):
            raise MXNetError("MultivariateNormal takes one of cov/scale_tril")
        tril = jnp.linalg.cholesky(_data(cov)) if scale_tril is None \
            else _data(scale_tril)
        super().__init__(loc=loc, scale_tril=tril)

    def _sample_shape(self, size):
        base = jnp.broadcast_shapes(self._p("loc").shape,
                                    self._p("scale_tril").shape[:-1])
        if size is None:
            return base
        size = (size,) if isinstance(size, int) else tuple(size)
        return size + base

    def _sample_impl(self, key, shape, loc, tril):
        eps = jax.random.normal(key, shape)
        return loc + jnp.einsum("...ij,...j->...i", tril, eps)

    def _log_prob_impl(self, v, loc, tril):
        d = v.shape[-1]
        diff = v - loc
        sol = jax.scipy.linalg.solve_triangular(tril, diff[..., None],
                                                lower=True)[..., 0]
        logdet = jnp.log(jnp.abs(jnp.diagonal(tril, axis1=-2,
                                              axis2=-1))).sum(-1)
        return -0.5 * (sol * sol).sum(-1) - logdet \
            - 0.5 * d * math.log(2 * math.pi)

    def _mean_impl(self, loc, tril):
        return loc


def _prob_or_logit(prob, logit):
    """Reference prob/logit duality (utils.py prob2logit/logit2prob):
    exactly one must be given. Returns ``(prob, logit)`` as NDArrays with
    the derived side computed THROUGH the op funnel, so whichever
    parameter the caller recorded keeps its tape identity and gradients
    flow to it (the base-class contract every distribution honors)."""
    if (prob is None) == (logit is None):
        raise MXNetError("specify exactly one of prob/logit")
    eps = 1e-7
    if prob is not None:
        pn = prob if isinstance(prob, NDArray) \
            else NDArray(jnp.asarray(prob, jnp.float32))

        def p2l(p):
            pc = jnp.clip(p, eps, 1 - eps)
            return jnp.log(pc) - jnp.log1p(-pc)
        return pn, _op("prob2logit", p2l, [pn])
    ln = logit if isinstance(logit, NDArray) \
        else NDArray(jnp.asarray(logit, jnp.float32))
    return _op("logit2prob", lambda lg: 1 / (1 + jnp.exp(-lg)), [ln]), ln


class Binomial(Distribution):
    """Binomial(n, prob) (reference distributions/binomial.py). ``n`` is a
    static Python int (static shapes: a data-dependent trial count cannot
    be compiled)."""

    def __init__(self, n=1, prob=None, logit=None):
        self.n = int(n)
        p, lg = _prob_or_logit(prob, logit)
        super().__init__(prob=p)
        self.logit = lg

    def _sample_impl(self, key, shape, prob):
        u = jax.random.uniform(key, (self.n,) + shape)
        return jnp.sum(u < prob, axis=0).astype(jnp.float32)

    def _log_prob_impl(self, v, prob):
        eps = 1e-7
        p = jnp.clip(prob, eps, 1 - eps)
        n = float(self.n)
        return (lax.lgamma(n + 1.) - lax.lgamma(v + 1.)
                - lax.lgamma(n - v + 1.)
                + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def _mean_impl(self, prob):
        return self.n * prob

    def _variance_impl(self, prob):
        return self.n * prob * (1 - prob)


class NegativeBinomial(Distribution):
    """NegativeBinomial(n, prob) counting occurrences at per-trial
    probability ``prob`` against ``n`` stopping failures (reference
    distributions/negative_binomial.py: mean = n*p/(1-p)). Sampling is
    the Gamma-Poisson mixture — two MXU-friendly primitives instead of a
    sequential trial loop."""

    def __init__(self, n, prob=None, logit=None):
        p, lg = _prob_or_logit(prob, logit)
        super().__init__(n=n, prob=p)
        self.logit = lg

    def _sample_impl(self, key, shape, n, prob):
        kg, kp = jax.random.split(key)
        eps = 1e-7
        rate = jnp.clip(prob, eps, 1 - eps) / jnp.clip(1 - prob, eps, 1.)
        lam = jax.random.gamma(kg, jnp.broadcast_to(n, shape)) * rate
        return jax.random.poisson(kp, lam).astype(jnp.float32)

    def _log_prob_impl(self, v, n, prob):
        eps = 1e-7
        p = jnp.clip(prob, eps, 1 - eps)
        return (lax.lgamma(v + n) - lax.lgamma(v + 1.) - lax.lgamma(n)
                + n * jnp.log1p(-p) + v * jnp.log(p))

    def _mean_impl(self, n, prob):
        return n * prob / (1 - prob)

    def _variance_impl(self, n, prob):
        return n * prob / (1 - prob) ** 2


class Multinomial(Distribution):
    """Multinomial(num_events, prob/logit, total_count) (reference
    distributions/multinomial.py). event_dim=1: the trailing axis is the
    category count vector."""

    event_dim = 1

    def __init__(self, num_events, prob=None, logit=None, total_count=1):
        self.num_events = int(num_events)
        self.total_count = int(total_count)
        p, lg = _prob_or_logit(prob, logit)
        super().__init__(prob=p)
        self.logit = lg

    def _sample_shape(self, size):
        base = self._p("prob").shape
        if size is None:
            return base
        size = (size,) if isinstance(size, int) else tuple(size)
        return size + base

    def _sample_impl(self, key, shape, prob):
        logits = jnp.log(jnp.clip(prob, 1e-7, 1.0))
        draws = jax.random.categorical(
            key, logits, shape=(self.total_count,) + shape[:-1])
        onehot = jax.nn.one_hot(draws, self.num_events)
        return jnp.sum(onehot, axis=0)

    def _log_prob_impl(self, v, prob):
        p = jnp.clip(prob, 1e-7, 1.0)
        n = float(self.total_count)
        return (lax.lgamma(n + 1.)
                - jnp.sum(lax.lgamma(v + 1.), axis=-1)
                + jnp.sum(v * jnp.log(p), axis=-1))

    def _mean_impl(self, prob):
        return self.total_count * prob

    def _variance_impl(self, prob):
        return self.total_count * prob * (1 - prob)


class FisherSnedecor(Distribution):
    """F-distribution (reference distributions/fishersnedecor.py):
    ratio of scaled chi-squares, sampled via two gamma draws."""

    def __init__(self, df1, df2):
        super().__init__(df1=df1, df2=df2)

    def _sample_impl(self, key, shape, df1, df2):
        k1, k2 = jax.random.split(key)
        x1 = jax.random.gamma(k1, jnp.broadcast_to(df1 / 2, shape)) * 2
        x2 = jax.random.gamma(k2, jnp.broadcast_to(df2 / 2, shape)) * 2
        return (x1 / df1) / (x2 / df2)

    def _log_prob_impl(self, v, df1, df2):
        h1, h2 = df1 / 2, df2 / 2
        return (h1 * jnp.log(df1) + h2 * jnp.log(df2)
                + (h1 - 1) * jnp.log(v)
                - (h1 + h2) * jnp.log(df2 + df1 * v)
                - (lax.lgamma(h1) + lax.lgamma(h2)
                   - lax.lgamma(h1 + h2)))

    def _mean_impl(self, df1, df2):
        return jnp.where(df2 > 2, df2 / (df2 - 2), jnp.nan)

    def _variance_impl(self, df1, df2):
        num = 2 * df2 ** 2 * (df1 + df2 - 2)
        den = df1 * (df2 - 2) ** 2 * (df2 - 4)
        return jnp.where(df2 > 4, num / den, jnp.nan)


class Independent(Distribution):
    """Reinterpret the last ``reinterpreted_batch_ndims`` batch dims of a
    base distribution as event dims: log_prob sums over them (reference
    distributions/independent.py)."""

    def __init__(self, base_distribution: Distribution,
                 reinterpreted_batch_ndims: int):
        self.base_dist = base_distribution
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        super().__init__()
        self.event_dim = getattr(base_distribution, "event_dim", 0) \
            + self.reinterpreted_batch_ndims

    def sample(self, size=None) -> NDArray:
        return self.base_dist.sample(size)

    def sample_n(self, size=None):
        return self.base_dist.sample_n(size)

    def log_prob(self, value) -> NDArray:
        lp = self.base_dist.log_prob(value)
        n = self.reinterpreted_batch_ndims
        return _op("independent_sum",
                   lambda x: _sum_rightmost(x, n), [lp])

    def entropy(self) -> NDArray:
        ent = self.base_dist.entropy()
        n = self.reinterpreted_batch_ndims
        return _op("independent_sum",
                   lambda x: _sum_rightmost(x, n), [ent])

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance


# ---------------------------------------------------------------------------
# KL divergence registry (reference probability/distributions/divergence.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> NDArray:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (tp, tq), f in _KL_REGISTRY.items():
            if isinstance(p, tp) and isinstance(q, tq):
                fn = f
                break
    if fn is None:
        raise MXNetError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def fn(pl, ps, ql, qs):
        vr = (ps / qs) ** 2
        return 0.5 * (vr + ((pl - ql) / qs) ** 2 - 1 - jnp.log(vr))
    return _op("kl_normal", fn, [p.loc, p.scale, q.loc, q.scale])


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qp):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return pp * (jnp.log(pp) - jnp.log(qp)) + \
            (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))
    return _op("kl_bernoulli", fn, [p.prob, q.prob])


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def fn(pl, ql):
        plog = jax.nn.log_softmax(pl, -1)
        qlog = jax.nn.log_softmax(ql, -1)
        return (jnp.exp(plog) * (plog - qlog)).sum(-1)
    return _op("kl_categorical", fn, [p.logit, q.logit])


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def fn(ps, qs):
        r = qs / ps
        return jnp.log(r) + 1 / r - 1
    return _op("kl_exponential", fn, [p.scale, q.scale])


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def fn(pl, ph, ql, qh):
        inside = (ql <= pl) & (qh >= ph)
        return jnp.where(inside, jnp.log((qh - ql) / (ph - pl)), jnp.inf)
    return _op("kl_uniform", fn, [p.low, p.high, q.low, q.high])
