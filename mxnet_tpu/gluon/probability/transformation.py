"""Invertible transformations + TransformedDistribution (reference
python/mxnet/gluon/probability/transformation/transformation.py and
distributions/transformed_distribution.py).

Each Transformation is a pure jnp bijection with a tractable
log|det J|; TransformedDistribution composes them over a base
distribution with the change-of-variables rule
``log p(y) = log p_base(x) - sum log|det J_i|``. Everything flows through
the op invoke funnel so transformed densities are differentiable on the
tape and fusable by XLA like any other op.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from .distributions import Distribution, _op, _sum_rightmost

__all__ = ["Transformation", "ComposeTransform", "ExpTransform",
           "AffineTransform", "PowerTransform", "SigmoidTransform",
           "SoftmaxTransform", "AbsTransform", "TransformedDistribution",
           "RelaxedBernoulli", "RelaxedOneHotCategorical"]


class Transformation:
    """Bijection y = f(x) with log|det J| (reference Transformation).
    ``t(x)`` applies forward; ``t.inv`` is the inverse transformation;
    ``t.log_det_jacobian(x, y)`` evaluates log|dy/dx|."""

    bijective = True
    event_dim = 0  # dims consumed by one application (0 = elementwise)
    sign = 1       # monotonicity sign for cdf routing, when defined

    def __call__(self, x):
        return _op(f"{type(self).__name__}_fwd", self._forward, [x])

    def _inv_call(self, y):
        return _op(f"{type(self).__name__}_inv", self._inverse, [y])

    @property
    def inv(self) -> "Transformation":
        return _InverseTransformation(self)

    def log_det_jacobian(self, x, y) -> NDArray:
        if not self.bijective:
            raise MXNetError(
                f"{type(self).__name__} is not bijective; log_det_jacobian "
                "is undefined")
        return _op(f"{type(self).__name__}_logdet",
                   self._log_det, [x, y])

    # hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _log_det(self, x, y):
        raise NotImplementedError


class _InverseTransformation(Transformation):
    """The inverse view of a transformation (reference
    _InverseTransformation): swaps forward/inverse and negates the
    jacobian log-determinant."""

    def __init__(self, base: Transformation):
        self._base = base
        self.bijective = base.bijective
        self.event_dim = base.event_dim
        self.sign = base.sign

    def __call__(self, x):
        return self._base._inv_call(x)

    def _inv_call(self, y):
        return self._base(y)

    @property
    def inv(self):
        return self._base

    def log_det_jacobian(self, x, y):
        neg = self._base.log_det_jacobian(y, x)
        return _op("negative", jnp.negative, [neg])


class ComposeTransform(Transformation):
    """Apply transforms left-to-right (reference ComposeTransform)."""

    def __init__(self, parts: Sequence[Transformation]):
        self.parts = list(parts)
        self.bijective = all(p.bijective for p in self.parts)
        self.event_dim = max((p.event_dim for p in self.parts), default=0)
        s = 1
        for p in self.parts:
            s *= p.sign
        self.sign = s

    def __call__(self, x):
        for p in self.parts:
            x = p(x)
        return x

    def _inv_call(self, y):
        for p in reversed(self.parts):
            y = p._inv_call(y)
        return y

    @property
    def inv(self):
        return ComposeTransform([p.inv for p in reversed(self.parts)])

    def log_det_jacobian(self, x, y):
        # re-walk the chain to recover intermediates
        xs: List = [x]
        for p in self.parts[:-1]:
            xs.append(p(xs[-1]))
        xs.append(y)
        total = None
        for p, a, b in zip(self.parts, xs[:-1], xs[1:]):
            ld = p.log_det_jacobian(a, b)
            # align event dims: a part with smaller event_dim contributes
            # elementwise and must be summed to this transform's event rank
            extra = self.event_dim - p.event_dim
            if extra:
                ld = _op("sum_rightmost",
                         lambda v, n=extra: _sum_rightmost(v, n), [ld])
            total = ld if total is None else total + ld
        return total


class ExpTransform(Transformation):
    """y = exp(x) (reference ExpTransform)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _log_det(self, x, y):
        return x


class AffineTransform(Transformation):
    """y = loc + scale * x (reference AffineTransform). loc/scale ride
    the op funnel as INPUTS, so recorded parameters receive gradients —
    learned affine flows train (the tape only sees explicit op inputs)."""

    def __init__(self, loc, scale, event_dim: int = 0):
        self.loc = loc
        self.scale = scale
        self.event_dim = event_dim

    def __call__(self, x):
        return _op("AffineTransform_fwd",
                   lambda xx, l, s: l + s * xx, [x, self.loc, self.scale])

    def _inv_call(self, y):
        return _op("AffineTransform_inv",
                   lambda yy, l, s: (yy - l) / s, [y, self.loc, self.scale])

    @property
    def sign(self):
        import numpy as onp
        s = self.scale
        s = onp.asarray(s.asnumpy() if isinstance(s, NDArray) else s)
        if (s > 0).all():
            return 1
        if (s < 0).all():
            return -1
        raise MXNetError("AffineTransform with mixed-sign scale has no "
                         "single monotonicity sign")

    def log_det_jacobian(self, x, y):
        ed = self.event_dim

        def fn(xx, l, s):
            ld = jnp.broadcast_to(jnp.log(jnp.abs(s)), xx.shape)
            return _sum_rightmost(ld, ed)
        return _op("AffineTransform_logdet", fn, [x, self.loc, self.scale])


class PowerTransform(Transformation):
    """y = x ** exponent on positives (reference PowerTransform)."""

    def __init__(self, exponent):
        if exponent == 0:
            raise MXNetError("PowerTransform exponent must be nonzero")
        self.exponent = exponent
        # on the positive domain x^e is increasing iff e > 0 (cdf routing)
        self.sign = 1 if exponent > 0 else -1

    def _forward(self, x):
        return jnp.power(x, self.exponent)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.exponent)

    def _log_det(self, x, y):
        return jnp.log(jnp.abs(self.exponent * y / x))


class SigmoidTransform(Transformation):
    """y = sigmoid(x) (reference SigmoidTransform)."""

    def _forward(self, x):
        return jnp.clip(1 / (1 + jnp.exp(-x)), 1e-7, 1 - 1e-7)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _log_det(self, x, y):
        # -softplus(-x) - softplus(x)
        return -jnp.logaddexp(0.0, -x) - jnp.logaddexp(0.0, x)


class SoftmaxTransform(Transformation):
    """y = softmax(x, -1): normalizing, NOT bijective (reference
    SoftmaxTransform)."""

    bijective = False
    event_dim = 1

    def _forward(self, x):
        z = x - jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(z)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)  # one representative pre-image


class AbsTransform(Transformation):
    """y = |x|: NOT bijective; inverse returns the positive pre-image
    (reference AbsTransform)."""

    bijective = False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class TransformedDistribution(Distribution):
    """Distribution of y = f_k(...f_1(x)) for x ~ base (reference
    transformed_distribution.py)."""

    def __init__(self, base_dist: Distribution, transforms):
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self.base_dist = base_dist
        self.transforms = list(transforms)
        for t in self.transforms:
            if not t.bijective:
                raise MXNetError(
                    f"{type(t).__name__} is not bijective — a transformed "
                    "density needs invertibility")
        super().__init__()

    def sample(self, size=None) -> NDArray:
        x = self.base_dist.sample(size)
        for t in self.transforms:
            x = t(x)
        return x

    def sample_n(self, size=None):
        return self.sample(size)

    def log_prob(self, value) -> NDArray:
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(value, jnp.float32))
        # walk backwards through inverses, accumulating log|det J|; every
        # contribution is summed up to the OVERALL event rank — the max of
        # the base law's and every transform's (reference
        # transformed_distribution.py event_dim bookkeeping)
        base_ed = getattr(self.base_dist, "event_dim", 0)
        event_dim = max([base_ed] + [t.event_dim for t in self.transforms])
        lp = None
        y = value
        for t in reversed(self.transforms):
            x = t._inv_call(y)
            ld = t.log_det_jacobian(x, y)
            gap = event_dim - t.event_dim
            if gap > 0:
                ld = _op("sum_rightmost",
                         lambda v, n=gap: _sum_rightmost(v, n), [ld])
            lp = ld if lp is None else lp + ld
            y = x  # next (outer-to-inner) inverse consumes this x
        base_lp = self.base_dist.log_prob(y)
        gap = event_dim - base_ed
        if gap > 0:
            base_lp = _op("sum_rightmost",
                          lambda v, n=gap: _sum_rightmost(v, n), [base_lp])
        return base_lp - lp if lp is not None else base_lp

    def cdf(self, value) -> NDArray:
        x = value
        sign = 1
        for t in reversed(self.transforms):
            sign *= t.sign
            x = t._inv_call(x)
        base_cdf = self.base_dist.cdf(x)
        if sign == 1:
            return base_cdf
        return _op("one_minus", lambda c: 1.0 - c, [base_cdf])

    def icdf(self, value) -> NDArray:
        sign = 1
        for t in self.transforms:
            sign *= t.sign
        if sign != 1:
            value = _op("one_minus", lambda c: 1.0 - c, [value])
        x = self.base_dist.icdf(value)
        for t in self.transforms:
            x = t(x)
        return x


# ---------------------------------------------------------------------------
# Relaxed (Concrete) distributions: differentiable discrete surrogates,
# built exactly like the reference — a logit-space base distribution under
# a squashing transform (reference relaxed_bernoulli.py /
# relaxed_one_hot_categorical.py)
# ---------------------------------------------------------------------------

import jax as _jax
from jax import lax

from .distributions import _prob_or_logit


class _LogitRelaxedBernoulli(Distribution):
    """Unnormalized logit-space relaxed Bernoulli (reference
    _LogitRelaxedBernoulli): x = (logit + logistic noise) / T."""

    def __init__(self, T, prob=None, logit=None):
        # shared duality helper: the given side keeps its tape identity,
        # the derived side flows through the op funnel
        _, logit = _prob_or_logit(prob, logit)
        super().__init__(T=T, logit=logit)

    def _sample_impl(self, key, shape, T, logit):
        u = _jax.random.uniform(key, shape, minval=1e-7, maxval=1 - 1e-7)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return (logit + logistic) / T

    def _log_prob_impl(self, v, T, logit):
        # density of the logit of a binary Concrete variable
        diff = logit - v * T
        return jnp.log(T) + diff - 2 * jnp.logaddexp(0.0, diff)


class RelaxedBernoulli(TransformedDistribution):
    """Concrete/Gumbel-sigmoid relaxation of Bernoulli at temperature T
    (reference RelaxedBernoulli = _LogitRelaxedBernoulli + sigmoid).
    Samples live in (0, 1) and gradients flow through them."""

    def __init__(self, T, prob=None, logit=None):
        base = _LogitRelaxedBernoulli(T, prob=prob, logit=logit)
        super().__init__(base, SigmoidTransform())
        self.T = base.T
        self.logit = base.logit


class _ExpRelaxedCategorical(Distribution):
    """log-space relaxed categorical (reference
    _ExpRelaxedCategorical): x = log_softmax((logits + Gumbel) / T)."""

    event_dim = 1

    def __init__(self, num_events, T, prob=None, logit=None):
        self.num_events = int(num_events)
        if (prob is None) == (logit is None):
            raise MXNetError("specify exactly one of prob/logit")
        if logit is None:
            logit = _op("prob2logit",
                        lambda p: jnp.log(jnp.clip(p, 1e-7, 1.0)), [prob])
        super().__init__(T=T, logit=logit)

    def _sample_shape(self, size):
        base = self._p("logit").shape
        if size is None:
            return base
        size = (size,) if isinstance(size, int) else tuple(size)
        return size + base

    def _sample_impl(self, key, shape, T, logit):
        g = _jax.random.gumbel(key, shape)
        z = (logit + g) / T
        return z - _jax.scipy.special.logsumexp(z, axis=-1, keepdims=True)

    def _log_prob_impl(self, v, T, logit):
        # ExpConcrete density (Maddison et al. 2017, eq. 22): for y on the
        # log-simplex, log p = log((n-1)!) + (n-1) log T
        #   + sum_i(logit_i - T y_i) - n * logsumexp_i(logit_i - T y_i)
        n = self.num_events
        score = logit - v * T
        return (lax.lgamma(jnp.asarray(float(n)))
                + (n - 1) * jnp.log(T)
                + score.sum(-1)
                - n * _jax.scipy.special.logsumexp(score, axis=-1))


class RelaxedOneHotCategorical(TransformedDistribution):
    """Concrete relaxation of OneHotCategorical at temperature T
    (reference RelaxedOneHotCategorical = _ExpRelaxedCategorical + exp).
    Samples live on the interior of the simplex."""

    def __init__(self, T, num_events=None, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("specify exactly one of prob/logit")
        if num_events is None:
            import numpy as _onp
            ref = prob if prob is not None else logit
            num_events = int(_onp.shape(
                ref.asnumpy() if isinstance(ref, NDArray) else ref)[-1])
        base = _ExpRelaxedCategorical(num_events, T, prob=prob, logit=logit)
        super().__init__(base, ExpTransform())
        self.T = base.T
        self.logit = base.logit
