"""Constraints on distribution parameters/supports (reference
python/mxnet/gluon/probability/distributions/constraint.py).

``check(value)`` validates eagerly and returns the value (host-side
numpy check: parameter validation is a construction-time concern, never
part of the compiled step — the TPU-native reading of the reference's
``npx.constraint_check`` op)."""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray

__all__ = ["Constraint", "Real", "Boolean", "Interval", "OpenInterval",
           "HalfOpenInterval", "IntegerInterval", "IntegerOpenInterval",
           "IntegerHalfOpenInterval", "GreaterThan", "GreaterThanEq",
           "LessThan", "LessThanEq", "Positive", "NonNegative",
           "PositiveInteger", "NonNegativeInteger", "UnitInterval",
           "Simplex", "LowerTriangular", "LowerCholesky",
           "PositiveDefinite", "is_dependent", "dependent"]


def _np(value):
    return value.asnumpy() if isinstance(value, NDArray) \
        else onp.asarray(value)


class Constraint:
    """Base constraint: ``check(v)`` raises MXNetError on violation and
    returns ``v`` unchanged otherwise (reference Constraint.check)."""

    _err = "constraint violated"

    def _ok(self, v: onp.ndarray) -> bool:
        raise NotImplementedError

    def check(self, value):
        if not bool(self._ok(_np(value))):
            raise MXNetError(
                f"Constraint violated: {self._err} ({type(self).__name__})")
        return value

    def __repr__(self):
        return type(self).__name__


class _Dependent(Constraint):
    """Placeholder whose meaning depends on other parameters (reference
    _Dependent); checking it directly is an error."""

    def check(self, value):
        raise MXNetError("cannot check a dependent constraint directly")


dependent = _Dependent()


def is_dependent(constraint) -> bool:
    return isinstance(constraint, _Dependent)


class Real(Constraint):
    _err = "value must be a real tensor (no NaN)"

    def _ok(self, v):
        return not onp.isnan(v).any()


class Boolean(Constraint):
    _err = "value must be 0 or 1"

    def _ok(self, v):
        return onp.isin(v, (0, 1)).all()


class Interval(Constraint):
    def __init__(self, lower_bound, upper_bound):
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self._err = f"value must be in [{lower_bound}, {upper_bound}]"

    def _ok(self, v):
        return ((v >= self.lower_bound) & (v <= self.upper_bound)).all()


class OpenInterval(Interval):
    def __init__(self, lower_bound, upper_bound):
        super().__init__(lower_bound, upper_bound)
        self._err = f"value must be in ({lower_bound}, {upper_bound})"

    def _ok(self, v):
        return ((v > self.lower_bound) & (v < self.upper_bound)).all()


class HalfOpenInterval(Interval):
    def __init__(self, lower_bound, upper_bound):
        super().__init__(lower_bound, upper_bound)
        self._err = f"value must be in [{lower_bound}, {upper_bound})"

    def _ok(self, v):
        return ((v >= self.lower_bound) & (v < self.upper_bound)).all()


class _IntegerMixin:
    @staticmethod
    def _integral(v):
        return (v == onp.floor(v)).all()


class IntegerInterval(Interval, _IntegerMixin):
    def _ok(self, v):
        return self._integral(v) and super()._ok(v)


class IntegerOpenInterval(OpenInterval, _IntegerMixin):
    def _ok(self, v):
        return self._integral(v) and super()._ok(v)


class IntegerHalfOpenInterval(HalfOpenInterval, _IntegerMixin):
    def _ok(self, v):
        return self._integral(v) and super()._ok(v)


class GreaterThan(Constraint):
    def __init__(self, lower_bound):
        self.lower_bound = lower_bound
        self._err = f"value must be > {lower_bound}"

    def _ok(self, v):
        return (v > self.lower_bound).all()


class GreaterThanEq(Constraint):
    def __init__(self, lower_bound):
        self.lower_bound = lower_bound
        self._err = f"value must be >= {lower_bound}"

    def _ok(self, v):
        return (v >= self.lower_bound).all()


class LessThan(Constraint):
    def __init__(self, upper_bound):
        self.upper_bound = upper_bound
        self._err = f"value must be < {upper_bound}"

    def _ok(self, v):
        return (v < self.upper_bound).all()


class LessThanEq(Constraint):
    def __init__(self, upper_bound):
        self.upper_bound = upper_bound
        self._err = f"value must be <= {upper_bound}"

    def _ok(self, v):
        return (v <= self.upper_bound).all()


class Positive(GreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegative(GreaterThanEq):
    def __init__(self):
        super().__init__(0)


class PositiveInteger(Positive, _IntegerMixin):
    def _ok(self, v):
        return self._integral(v) and super()._ok(v)


class NonNegativeInteger(NonNegative, _IntegerMixin):
    def _ok(self, v):
        return self._integral(v) and super()._ok(v)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0, 1)


class Simplex(Constraint):
    _err = "value must be non-negative and sum to 1 on the last axis"

    def _ok(self, v):
        return (v >= 0).all() and \
            onp.allclose(v.sum(-1), 1.0, atol=1e-5)


class LowerTriangular(Constraint):
    _err = "value must be lower-triangular"

    def _ok(self, v):
        return onp.allclose(v, onp.tril(v))


class LowerCholesky(Constraint):
    _err = "value must be lower-triangular with positive diagonal"

    def _ok(self, v):
        return onp.allclose(v, onp.tril(v)) and \
            (onp.diagonal(v, axis1=-2, axis2=-1) > 0).all()


class PositiveDefinite(Constraint):
    _err = "value must be symmetric positive-definite"

    def _ok(self, v):
        if not onp.allclose(v, onp.swapaxes(v, -1, -2), atol=1e-6):
            return False
        try:
            onp.linalg.cholesky(v)
            return True
        except onp.linalg.LinAlgError:
            return False
