"""StochasticBlock (reference: python/mxnet/gluon/probability/block/
stochastic_block.py): a HybridBlock that can collect auxiliary losses
(e.g. KL terms in a VAE) from inside forward.
"""
from __future__ import annotations

import functools

from ..block import HybridBlock

__all__ = ["StochasticBlock"]


class StochasticBlock(HybridBlock):
    """HybridBlock with ``add_loss`` collection. Decorate forward with
    ``StochasticBlock.collectLoss`` to expose ``(out, losses)``."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._losscache = []

    def add_loss(self, loss):
        self._losscache.append(loss)

    @staticmethod
    def collectLoss(forward_fn):
        @functools.wraps(forward_fn)
        def wrapped(self, *args, **kwargs):
            self._losscache = []
            out = forward_fn(self, *args, **kwargs)
            self._losses = list(self._losscache)
            self._losscache = []
            return out
        return wrapped

    @property
    def losses(self):
        return self._losses
