"""PipelineTrainer: Gluon-facing GPipe pipeline parallelism (VERDICT r2
item 9; schedule from parallel/pipeline.py — no reference analog, the
reference only had manual per-ctx layer placement,
docs model_parallel_lstm.md).

``PipelineTrainer`` takes a ``HybridSequential`` whose children partition
into ``num_stages`` structurally-identical stages, a microbatch count,
and standard Trainer arguments. ``forward_backward(x, y)`` runs ONE
compiled program: microbatches stream through the stage ring
(lax.ppermute inside lax.scan, sharded over a 'pp' mesh axis), the loss
is taken over the reassembled batch, and reverse-mode through the
schedule produces the stage gradients. The gradients land in each
Parameter's ``.grad`` exactly as ``loss.backward()`` would leave them, so
the inherited ``Trainer.step()`` — optimizer decision matrix, rescale,
fused multi-tensor update — applies unchanged.

Constraint (same as parallel/pipeline.py): stages must share a parameter
tree structure and preserve activation shape — the N-identical-blocks
regime pipeline parallelism exists for. BatchNorm computes per-microbatch
statistics under pipelining (the standard GPipe caveat).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .trainer import Trainer

__all__ = ["PipelineTrainer"]


class PipelineTrainer(Trainer):
    def __init__(self, net, optimizer, optimizer_params=None,
                 num_stages: Optional[int] = None,
                 num_microbatches: int = 4, loss=None, mesh=None,
                 **kwargs):
        from .block import HybridBlock
        children = list(net._children.values())
        if not children:
            raise MXNetError("PipelineTrainer needs a non-empty Sequential")
        if num_stages is None:
            num_stages = len(children)
        if len(children) % num_stages:
            raise MXNetError(
                f"{len(children)} blocks do not partition into "
                f"{num_stages} equal stages")
        per = len(children) // num_stages
        self._stages: List[List[HybridBlock]] = [
            children[i * per:(i + 1) * per] for i in range(num_stages)]
        self._num_stages = num_stages
        self._num_micro = num_microbatches
        self._loss = loss
        self._net = net

        # stage parameter lists, stage-major, identical structure required
        stage_params = []
        for blocks in self._stages:
            ps = []
            for b in blocks:
                ps.extend(b.collect_params().values())
            stage_params.append(ps)
        shapes0 = [tuple(p.shape) for p in stage_params[0]]
        for si, ps in enumerate(stage_params[1:], 1):
            if [tuple(p.shape) for p in ps] != shapes0:
                raise MXNetError(
                    f"stage {si} parameter shapes differ from stage 0 — "
                    "the GPipe ring needs structurally identical stages")
        self._stage_params = stage_params
        flat = [p for ps in stage_params for p in ps]
        super().__init__(flat, optimizer, optimizer_params, **kwargs)

        if mesh is None:
            from jax.sharding import Mesh
            devs = jax.devices()
            pp = num_stages if len(devs) >= num_stages else 1
            mesh = Mesh(onp.array(devs[:pp]), ("pp",))
        self._mesh = mesh
        self._grad_fn = None

    # ---------------- compiled pipeline step ----------------
    def _stage_fn(self, params_leaves, x_data):
        """Run ONE stage's blocks with ``params_leaves`` bound in (the
        _functional_apply trick): stage 0's block structure hosts every
        stage's weights — structures are identical by construction."""
        from .. import _tape
        blocks = self._stages[0]
        owners = self._stage_params[0]
        orig = [p._data for p in owners]
        for p, d in zip(owners, params_leaves):
            p._data = NDArray(d)
        prev = _tape.set_recording(False)
        try:
            h = NDArray(x_data)
            for b in blocks:
                h = b(h)
        finally:
            for p, o in zip(owners, orig):
                p._data = o
            _tape.set_recording(prev)
        return h._data

    def _loss_data(self, out_data, y_data):
        from .. import _tape
        prev = _tape.set_recording(False)
        try:
            if self._loss is None:
                return jnp.mean((NDArray(out_data)._data - y_data) ** 2)
            l = self._loss(NDArray(out_data), NDArray(y_data))
            return jnp.mean(l._data)
        finally:
            _tape.set_recording(prev)

    def _build_grad_fn(self):
        from ..parallel.pipeline import run_pipeline
        mesh = self._mesh
        micro = self._num_micro
        pp_devs = self._mesh.shape["pp"]

        def step(stacked, x, y):
            def loss_fn(stk):
                leaves = [stk[k] for k in range(len(self._stage_params[0]))]

                def stage_fn(stage_leaves, h):
                    return self._stage_fn(stage_leaves, h)

                if pp_devs == self._num_stages and pp_devs > 1:
                    out = run_pipeline(stage_fn, leaves, x, micro, mesh)
                else:
                    # degenerate mesh (single chip): same math, python
                    # loop over stages — keeps semantics identical where
                    # no 'pp' axis exists to shard over
                    out = x
                    for s in range(self._num_stages):
                        out = stage_fn([lf[s] for lf in leaves], out)
                return self._loss_data(out, y)

            loss, grads = jax.value_and_grad(loss_fn)(stacked)
            return loss, grads

        return jax.jit(step)

    def forward_backward(self, x, y):
        """One pipelined forward+backward; leaves gradients on the
        Parameters (like ``loss.backward()``) and returns the scalar
        loss NDArray. Follow with ``trainer.step(batch_size)``."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        if self._grad_fn is None:
            self._grad_fn = self._build_grad_fn()
        # stack stage-major: leaf k holds (num_stages, *shape_k), the
        # stage axis laid over the 'pp' mesh devices
        nleaf = len(self._stage_params[0])
        stacked = {
            k: jnp.stack([self._stage_params[s][k].data()._data
                          for s in range(self._num_stages)])
            for k in range(nleaf)}
        if self._mesh.shape["pp"] > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            pp_sh = NamedSharding(self._mesh, P("pp"))
            repl = NamedSharding(self._mesh, P())
            stacked = {k: jax.device_put(v, pp_sh)
                       for k, v in stacked.items()}
            x = jax.device_put(jnp.asarray(x), repl)
            y = jax.device_put(jnp.asarray(y), repl)
        loss, grads = self._grad_fn(stacked, x, y)
        dev0 = jax.devices()[0]
        for k in range(nleaf):
            for s in range(self._num_stages):
                p = self._stage_params[s][k]
                d = p.data()
                g = grads[k][s]
                if self._mesh.shape["pp"] > 1:
                    # un-shard: the optimizer update runs on the weight's
                    # own (single) device
                    g = jax.device_put(g, dev0)
                d._grad = NDArray(g)
                d.fresh_grad = True
        return NDArray(loss)
