"""Gluon Parameter: lazily-initialized, device-placed, grad-carrying weights.

Reference analog: python/mxnet/gluon/parameter.py (Parameter :366 _init_impl
per-ctx replicas, :398 _reduce, :527 row_sparse pull). TPU-native difference:
instead of N per-device replica arrays kept in sync by a kvstore, a Parameter
owns ONE logical NDArray which may carry a ``jax.sharding.NamedSharding`` —
replication/sharding across the mesh is a layout property of the single
array, and XLA inserts the collectives (SURVEY §2.3 "absorbed" notes).
``list_data``/``list_grad`` keep API parity for reference-style loops.
"""
from __future__ import annotations

import uuid
from typing import List, Optional

import numpy as onp

from .. import initializer as init_mod
from ..base import MXNetError, jx_dtype
from ..context import Context, current_context
from ..ndarray import ndarray as ndmod
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape inference completed
    (reference parameter.py DeferredInitializationError)."""


def _shape_incomplete(shape) -> bool:
    return shape is None or any(s in (0, -1, None) for s in shape)


class Parameter:
    """A weight/state tensor of a Block.

    grad_req: 'write' | 'add' | 'null' (reference semantics). Unknown dims
    (0/-1) defer allocation until shape inference at first forward.
    """

    def __init__(self, name: str = "weight", grad_req: str = "write",
                 shape=None, dtype="float32", lr_mult: float = 1.0,
                 wd_mult: float = 1.0, init=None, allow_deferred_init: bool = True,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default"):
        self._name = name
        self._uuid = str(uuid.uuid4())
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._stype = stype
        self._grad_stype = grad_stype
        self._data: Optional[NDArray] = None
        self._deferred_init_args = None
        self._sharding = None  # jax NamedSharding once attached to a mesh

    # ---------------- identity ----------------
    @property
    def name(self) -> str:
        return self._name

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape):
            raise MXNetError(
                f"cannot reset shape of {self.name}: rank mismatch "
                f"{self._shape} vs {new_shape}")
        merged = []
        for a, b in zip(self._shape, new_shape):
            if a in (0, -1, None):
                merged.append(b)
            elif b in (0, -1, None) or a == b:
                merged.append(a)
            else:
                raise MXNetError(
                    f"shape mismatch for {self.name}: {self._shape} vs {new_shape}")
        self._shape = tuple(merged)

    @property
    def stype(self):
        return self._stype

    # ---------------- initialization ----------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False):
        """Allocate + fill data (reference parameter.py initialize). With an
        incomplete shape, records deferred-init args and returns."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else current_context()
        default_init = default_init or init_mod.Uniform()
        initializer = init if init is not None else self.init
        if _shape_incomplete(self._shape):
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize {self.name}: shape {self._shape} "
                    f"incomplete and deferred init not allowed")
            self._deferred_init_args = (initializer, ctx, default_init)
            return
        self._finish_init(initializer, ctx, default_init)

    def _finish_init(self, initializer, ctx, default_init):
        if initializer is not None:
            # explicit initializer wins outright — no name-suffix dispatch
            # (reference: InitDesc attrs['__init__'] bypasses suffix rules)
            ini = init_mod.create(initializer)
            arr = NDArray(ini._init_weight(self._name, self._shape,
                                           jx_dtype(self.dtype)))
        else:
            ini = init_mod.create(default_init)
            arr = ini.init_array(self._name, self._shape,
                                 jx_dtype(self.dtype))
        self._data = NDArray(arr._data, ctx=ctx)
        self._deferred_init_args = None
        if self.grad_req != "null":
            self._data.attach_grad(self.grad_req)
        if self._sharding is not None:
            self._apply_sharding()

    def _finish_deferred_init(self):
        if self._deferred_init_args is None:
            raise DeferredInitializationError(
                f"parameter {self.name} not initialized; call initialize()")
        if _shape_incomplete(self._shape):
            raise DeferredInitializationError(
                f"parameter {self.name} shape {self._shape} still unknown")
        self._finish_init(*self._deferred_init_args)

    # ---------------- access ----------------
    def data(self, ctx=None) -> NDArray:
        if self._data is None:
            if self._deferred_init_args is not None:
                self._finish_deferred_init()
            else:
                raise DeferredInitializationError(
                    f"parameter {self.name} not initialized; call initialize()")
        return self._data

    def list_data(self) -> List[NDArray]:
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        d = self.data()
        if d.grad is None:
            raise MXNetError(
                f"parameter {self.name} has grad_req='null'; no gradient")
        return d.grad

    def list_grad(self) -> List[NDArray]:
        return [self.grad()]

    def list_ctx(self):
        return [self.data().context]

    def set_data(self, data):
        data = data if isinstance(data, NDArray) else NDArray(data)
        if self._data is None:
            self.shape = data.shape
            self._data = data
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req)
            return
        if data.shape != self._data.shape:
            raise MXNetError(
                f"shape mismatch setting {self.name}: {data.shape} vs "
                f"{self._data.shape}")
        self._data._data = data._data.astype(self._data._data.dtype)

    def _write_fused(self, new_data):
        """Write a fused-train-step result buffer into this parameter
        IN PLACE: the NDArray handle (and its attached grad / any user
        reference from ``data()``) stays stable, only the backing jax
        array is swapped — the writeback half of the donation contract
        (``Trainer.compile_step``; docs/PERF_NOTES.md)."""
        self._data._data = new_data

    def zero_grad(self):
        d = self._data
        if d is not None and d.grad is not None:
            d.grad._data = d.grad._data * 0

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data.grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._data.attach_grad(self.grad_req)

    # ---------------- sharding (TPU-native extension) ----------------
    def set_sharding(self, sharding):
        """Attach a jax NamedSharding; the single logical array is laid out
        across the mesh (replaces reference per-ctx replica lists)."""
        self._sharding = sharding
        if self._data is not None:
            self._apply_sharding()

    def _apply_sharding(self):
        import jax
        self._data._data = jax.device_put(self._data._data, self._sharding)
        if self._data.grad is not None:
            self._data.grad._data = jax.device_put(self._data.grad._data,
                                                   self._sharding)

    # ---------------- misc ----------------
    @property
    def var_name(self):
        return self._name

    def __repr__(self):
        return (f"Parameter {self._name} (shape={self._shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-differentiable constant parameter (reference gluon Constant)."""

    def __init__(self, value, name: str = "const"):
        value = value if isinstance(value, NDArray) else NDArray(value)
        super().__init__(name=name, grad_req="null", shape=value.shape,
                         dtype=str(onp.dtype(str(value._data.dtype))
                                   if str(value._data.dtype) != "bfloat16"
                                   else "bfloat16"),
                         init="zeros", differentiable=False)
        self._value = value
        self._data = value

    def initialize(self, *args, **kwargs):
        pass
