"""mx.npx — NumPy-extension namespace (operators beyond the NumPy standard).

Reference analog: python/mxnet/numpy_extension/ + ndarray/numpy_extension/
(`_npx.*` ops). Because the op funnel propagates the mx.np ndarray class to
outputs whenever an input is an mx.np array (ops/registry.set_np_ndarray_cls),
the npx surface simply re-exports the framework's nd-level kernels — calling
them with np arrays yields np arrays.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.special as jsp

from ..base import jx_dtype
from ..context import cpu, gpu, tpu, num_gpus, num_tpus, current_context
from ..ndarray.ndarray import NDArray, waitall
from ..ndarray.ops import (  # noqa: F401
    softmax, log_softmax, softmin, pick, topk, one_hot, gather_nd,
    scatter_nd, FullyConnected as fully_connected, Dropout as dropout,
    Embedding as embedding, Activation as activation, LeakyReLU as leaky_relu,
    SequenceMask as sequence_mask, batch_dot, cast, clip, shape_array,
    boolean_mask, stop_gradient, reshape_like, broadcast_like,
)
from ..ndarray.nn_ops import (  # noqa: F401
    Convolution as convolution, Deconvolution as deconvolution,
    Pooling as pooling, BatchNorm as batch_norm, LayerNorm as layer_norm,
    GroupNorm as group_norm, InstanceNorm as instance_norm,
)
from ..ops.registry import invoke_raw
from ..util import (  # noqa: F401
    set_np, reset_np, is_np_array, is_np_shape, set_np_shape, np_shape,
    np_array, use_np, use_np_array)
from ..numpy.multiarray import ndarray, array, _invoke

__all__ = [
    "set_np", "reset_np", "is_np_array", "is_np_shape", "softmax",
    "log_softmax", "masked_softmax", "masked_log_softmax", "pick", "topk",
    "one_hot", "gather_nd", "scatter_nd", "fully_connected", "convolution",
    "deconvolution", "pooling", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "dropout", "embedding", "activation", "leaky_relu",
    "sequence_mask", "batch_dot", "relu", "sigmoid", "erf", "erfinv",
    "gamma", "gammaln", "digamma", "smooth_l1", "arange_like", "waitall",
    "cpu", "gpu", "tpu", "num_gpus", "num_tpus", "current_context",
    "reshape_like", "broadcast_like", "stop_gradient", "boolean_mask",
    "cast", "clip", "shape_array", "seed", "index_update", "index_add",
]

from ..ndarray.random import seed  # noqa: F401,E402


def _arr(a):
    return a if isinstance(a, NDArray) else array(a)


def relu(data):
    return _invoke("npx_relu", lambda x: jnp.maximum(x, 0), [_arr(data)])


def sigmoid(data):
    return _invoke("npx_sigmoid", lambda x: 1.0 / (1.0 + jnp.exp(-x)),
                   [_arr(data)])


def erf(data):
    return _invoke("npx_erf", jsp.erf, [_arr(data)])


def erfinv(data):
    return _invoke("npx_erfinv", jsp.erfinv, [_arr(data)])


def gamma(data):
    """Gamma function Γ(x) (reference _npx.gamma)."""
    return _invoke("npx_gamma", lambda x: jnp.exp(jsp.gammaln(x)),
                   [_arr(data)])


def gammaln(data):
    return _invoke("npx_gammaln", jsp.gammaln, [_arr(data)])


def digamma(data):
    return _invoke("npx_digamma", jsp.digamma, [_arr(data)])


def smooth_l1(data, scalar=1.0):
    """Reference smooth_l1 (src/operator/tensor/elemwise_unary_op.cc):
    0.5 (σx)² if |x| < 1/σ² else |x| - 0.5/σ²."""
    s2 = scalar * scalar

    def fn(x):
        return jnp.where(jnp.abs(x) < 1.0 / s2,
                         0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)
    return _invoke("npx_smooth_l1", fn, [_arr(data)])


def arange_like(data, start=0.0, step=1.0, repeat=1, ctx=None, axis=None):
    def fn(x):
        n = x.shape[axis] if axis is not None else x.size
        vals = start + step * jnp.arange(n, dtype=jnp.float32)
        if axis is None:
            return vals.reshape(x.shape)
        return vals
    return _invoke("npx_arange_like", fn, [_arr(data)])


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    if mask is None:
        return softmax(_arr(data), axis=axis, temperature=temperature)

    def fn(x, m):
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else -1e30
        masked = jnp.where(m.astype(bool), x / temperature, neg)
        e = jnp.exp(masked - jnp.max(masked, axis=axis, keepdims=True))
        e = jnp.where(m.astype(bool), e, 0.0)
        return e / jnp.maximum(jnp.sum(e, axis=axis, keepdims=True), 1e-30)
    return _invoke("npx_masked_softmax", fn, [_arr(data), _arr(mask)])


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    if mask is None:
        return log_softmax(_arr(data), axis=axis, temperature=temperature)

    def fn(x, m):
        neg = jnp.finfo(x.dtype).min
        masked = jnp.where(m.astype(bool), x / temperature, neg)
        lse = jsp.logsumexp(masked, axis=axis, keepdims=True,
                            where=m.astype(bool))
        return jnp.where(m.astype(bool), masked - lse, -jnp.inf)
    return _invoke("npx_masked_log_softmax", fn, [_arr(data), _arr(mask)])


def index_update(data, indices, values):
    """Functional scatter-update: data[indices] = values (XLA scatter)."""
    v = values._data if isinstance(values, NDArray) else values
    idx = indices._data if isinstance(indices, NDArray) else indices
    return _invoke("npx_index_update",
                   lambda x: x.at[idx].set(v), [_arr(data)])


def index_add(data, indices, values):
    v = values._data if isinstance(values, NDArray) else values
    idx = indices._data if isinstance(indices, NDArray) else indices
    return _invoke("npx_index_add",
                   lambda x: x.at[idx].add(v), [_arr(data)])


# npx.image: image-op namespace (reference numpy_extension/__init__.py:23
# re-exports mxnet.ndarray.image) — the framework's image ops already
# propagate the mx.np array class through the invoke funnel.
from .. import image as image  # noqa: E402,F401


# reference npx re-exports util.get_cuda_compute_capability; keep ONE
# behavior for the symbol everywhere (the util compat shim: None on
# non-CUDA builds, so defensive `if cap and cap >= 70:` probes skip)
from ..util import get_cuda_compute_capability  # noqa: E402,F401
