"""Subgraph/partitioning backends for ``optimize_for`` (reference:
src/operator/subgraph/ — SubgraphProperty registry + BuildSubgraph pass,
build_subgraph.cc:726, surfaced as HybridBlock.optimize_for(backend=...),
python block.py:1141).

TPU-native redesign: a hybridized block is ONE traced XLA computation, so a
"backend" is a transformation of that traced callable rather than an
nnvm-graph partition — XLA then compiles the transformed program (its
fusion pass is the analog of the reference's MKLDNN/TensorRT subgraph
fusion, and it runs always). Built-in backends:

- ``remat``    — jax.checkpoint over the whole forward: recompute instead
                 of storing activations (HBM relief for big models).
- ``bf16``     — graph-level ReducePrecision (reference
                 src/nnvm/low_precision_pass.cc analog): float32 traced
                 inputs/params cast to bfloat16, outputs restored to f32.

Register custom backends with ``register_backend`` (the analog of
``SubgraphBackendRegistry``).
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["SubgraphBackend", "register_backend", "get_backend",
           "list_backends"]


class SubgraphBackend:
    """Transforms the traced forward callable of a hybridized block.

    ``transform(fn, static_argnums)`` receives the function jax.jit will
    compile (array args are leaves/params; ``static_argnums`` index
    non-array metadata) and returns a replacement with the SAME signature.
    """

    name = "base"

    def transform(self, fn: Callable, static_argnums=()) -> Callable:
        return fn


_BACKENDS: Dict[str, SubgraphBackend] = {}


def register_backend(name: str):
    """Decorator: register a SubgraphBackend class or instance."""
    def deco(obj):
        inst = obj() if isinstance(obj, type) else obj
        inst.name = name
        _BACKENDS[name] = inst
        return obj
    return deco


def get_backend(name: str) -> SubgraphBackend:
    if name not in _BACKENDS:
        raise MXNetError(f"subgraph backend {name!r} is not registered; "
                         f"available: {sorted(_BACKENDS)}")
    return _BACKENDS[name]


def list_backends():
    return sorted(_BACKENDS)


@register_backend("default")
class _DefaultBackend(SubgraphBackend):
    """No-op: XLA's always-on fusion is the default 'partitioner'."""


@register_backend("remat")
class _RematBackend(SubgraphBackend):
    def transform(self, fn, static_argnums=()):
        return jax.checkpoint(fn, static_argnums=tuple(static_argnums))


@register_backend("bf16")
class _BF16Backend(SubgraphBackend):
    """Whole-graph bf16 (ReducePrecision analog): f32 array inputs are
    cast down on entry and outputs cast back up on exit."""

    def transform(self, fn, static_argnums=()):
        static = set(static_argnums)

        def cast_down(x):
            if hasattr(x, "dtype") and x.dtype == jnp.float32:
                return x.astype(jnp.bfloat16)
            return x

        def cast_up(x):
            if hasattr(x, "dtype") and x.dtype == jnp.bfloat16:
                return x.astype(jnp.float32)
            return x

        def wrapped(*args):
            cast_args = tuple(
                a if i in static else jax.tree_util.tree_map(cast_down, a)
                for i, a in enumerate(args))
            out = fn(*cast_args)
            return jax.tree_util.tree_map(cast_up, out)

        return wrapped
