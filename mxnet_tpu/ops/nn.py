"""Neural-network ops: convolution, pooling, normalization.

Reference analog: src/operator/nn/ (~31k LoC: conv via im2col/cuDNN, pooling
kernels, batch/layer/group/instance norm CPU+CUDA kernels). TPU-native design:
everything lowers to XLA's native conv/reduce-window/reduce emitters —
`lax.conv_general_dilated` maps directly onto the MXU, and XLA fuses the
normalization arithmetic into surrounding ops, absorbing what the reference's
cuDNN/MKLDNN vendor layers did by hand (SURVEY §2.2 note).

Layout: the public API is NCHW/NCW/NCDHW like the reference ops; XLA's TPU
layout assignment transposes internally to the MXU-friendly layout, so we keep
API parity without a perf tax.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["conv", "conv_transpose", "pool", "global_pool", "batch_norm_infer",
           "batch_norm_train", "layer_norm", "group_norm", "instance_norm",
           "l2_norm", "lrn", "adaptive_avg_pool", "bilinear_resize"]


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    return t if len(t) == n else t + t[-1:] * (n - len(t))


def _conv_dn(ndim: int):
    """NC+spatial dimension numbers for lax.conv_general_dilated."""
    sp = "DHW"[3 - ndim:]
    return lax.conv_dimension_numbers(
        (1, 1) + (1,) * ndim,  # dummy shapes; only layout strings matter
        (1, 1) + (1,) * ndim,
        ("NC" + sp, "OI" + sp, "NC" + sp))


def conv(x, w, b=None, stride=None, dilate=None, pad=None, num_group: int = 1):
    """N-d convolution, NC+spatial layout (reference Convolution op,
    src/operator/nn/convolution.cc). Lowers to one XLA conv → MXU."""
    ndim = x.ndim - 2
    stride = _tup(stride, ndim)
    dilate = _tup(dilate, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    dn = _conv_dn(ndim)
    # NOTE: no preferred_element_type — the TPU MXU accumulates bf16 convs
    # in f32 internally regardless (one rounding at the output), and this
    # jax version's conv VJP mis-types the transposed conv when preferred
    # differs from the input dtype (bf16 primal vs f32 cotangent)
    out = lax.conv_general_dilated(
        x, w, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * ndim)
    return out


def conv_transpose(x, w, b=None, stride=None, dilate=None, pad=None,
                   adj=None, num_group: int = 1):
    """Transposed convolution (reference Deconvolution op). Implemented as
    the gradient of conv: lhs-dilated XLA conv."""
    ndim = x.ndim - 2
    stride = _tup(stride, ndim)
    dilate = _tup(dilate, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    adj = _tup(adj if adj is not None else 0, ndim)
    dn = _conv_dn(ndim)
    k = w.shape[2:]
    # effective kernel extent with dilation
    eff = [(kk - 1) * dd + 1 for kk, dd in zip(k, dilate)]
    padding = [(e - 1 - p, e - 1 - p + a)
               for e, p, a in zip(eff, pad, adj)]
    # flip spatial dims and swap I/O channels for the gradient-conv form
    wt = jnp.flip(w, axis=tuple(range(2, 2 + ndim)))
    if num_group > 1:
        o, i = wt.shape[0], wt.shape[1]
        wt = wt.reshape((num_group, o // num_group, i) + k)
        wt = jnp.swapaxes(wt, 1, 2)
        wt = wt.reshape((num_group * i, o // num_group) + k)
    else:
        wt = jnp.swapaxes(wt, 0, 1)
    out = lax.conv_general_dilated(
        x, wt, window_strides=(1,) * ndim,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * ndim)
    return out


def pool(x, kernel, pool_type: str = "max", stride=None, pad=None,
         count_include_pad: bool = True, ceil_mode: bool = False,
         p_value: int = 2):
    """Max/avg/sum/lp pooling via XLA reduce_window (reference Pooling op).
    ceil_mode ≙ reference pooling_convention='full': extra right-padding so
    the output size uses ceil instead of floor (src/operator/nn/pooling.cc)."""
    ndim = x.ndim - 2
    kernel = _tup(kernel, ndim)
    stride = _tup(stride if stride is not None else kernel, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    rpad = list(pad)
    if ceil_mode:
        for i in range(ndim):
            span = x.shape[2 + i] + 2 * pad[i] - kernel[i]
            rem = span % stride[i]
            if rem:
                rpad[i] = pad[i] + (stride[i] - rem)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple(
        (p, r) for p, r in zip(pad, rpad))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        # Denominator semantics (reference src/operator/nn/pool.h): with
        # count_include_pad the window is clipped to the explicitly-padded
        # extent [0, H+2p) — ceil_mode's extra right-padding never counts;
        # without it only real elements count. Both reduce to a constant
        # that XLA folds when no clipping can occur.
        if count_include_pad:
            cnt_shape = (1, 1) + tuple(x.shape[2 + i] + 2 * pad[i]
                                       for i in range(ndim))
            cnt_pad = ((0, 0), (0, 0)) + tuple(
                (0, r - p) for p, r in zip(pad, rpad))
            ones = jnp.ones(cnt_shape, x.dtype)
        else:
            ones = jnp.ones((1, 1) + x.shape[2:], x.dtype)
            cnt_pad = padding
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, cnt_pad)
        return s / cnt
    if pool_type == "lp":
        s = lax.reduce_window(jnp.abs(x) ** p_value, 0.0, lax.add, window,
                              strides, padding)
        return s ** (1.0 / p_value)
    raise MXNetError(f"unknown pool_type {pool_type}")


def global_pool(x, pool_type: str = "max"):
    axes = tuple(range(2, x.ndim))
    if pool_type == "max":
        return jnp.max(x, axis=axes, keepdims=True)
    if pool_type == "avg":
        return jnp.mean(x, axis=axes, keepdims=True)
    return jnp.sum(x, axis=axes, keepdims=True)


def adaptive_avg_pool(x, output_size):
    """Reference contrib.AdaptiveAvgPooling2D."""
    n, c, h, w = x.shape
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    if h % oh == 0 and w % ow == 0:
        x = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    # general case: interp-style averaging via image resize of the integral
    return jax.image.resize(x, (n, c, oh, ow), method="linear")


def bilinear_resize(x, height: int, width: int, align_corners: bool = False):
    """Reference contrib.BilinearResize2D."""
    n, c, h, w = x.shape
    return jax.image.resize(x, (n, c, height, width), method="linear")


def _bcast_stats(ndim, v):
    return v.reshape((1, -1) + (1,) * (ndim - 2))


def _stat_dtype(x):
    """Normalization statistics accumulate in f32 even when activations
    flow bf16/fp16 (AMP): same recipe as every production TPU BN — the
    low-precision tensor is only the storage format, never the reduction
    accumulator. f64 inputs keep f64."""
    return jnp.promote_types(x.dtype, jnp.float32)


def batch_norm_infer(x, gamma, beta, moving_mean, moving_var, eps: float):
    """Inference-mode BN: normalize with running stats (f32 arithmetic,
    output in the activation dtype)."""
    dt = _stat_dtype(x)
    xf = x.astype(dt)
    mm = _bcast_stats(x.ndim, moving_mean).astype(dt)
    mv = _bcast_stats(x.ndim, moving_var).astype(dt)
    g = _bcast_stats(x.ndim, gamma).astype(dt)
    b = _bcast_stats(x.ndim, beta).astype(dt)
    inv = lax.rsqrt(mv + eps)
    return ((xf - mm) * inv * g + b).astype(x.dtype)


def batch_norm_train(x, gamma, beta, eps: float):
    """Training-mode BN: returns (out, batch_mean, batch_var) so the layer
    can fold the running-stat update into the same compiled step
    (reference batch_norm.cc saves mean/var as aux outputs). Stats are
    f32; out keeps the activation dtype."""
    dt = _stat_dtype(x)
    xf = x.astype(dt)
    axes = (0,) + tuple(range(2, x.ndim))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    m = _bcast_stats(x.ndim, mean)
    v = _bcast_stats(x.ndim, var)
    g = _bcast_stats(x.ndim, gamma).astype(dt)
    b = _bcast_stats(x.ndim, beta).astype(dt)
    out = ((xf - m) * lax.rsqrt(v + eps) * g + b).astype(x.dtype)
    return out, mean, var


def layer_norm(x, gamma, beta, axis: int = -1, eps: float = 1e-5):
    """Reference LayerNorm (src/operator/nn/layer_norm.cc). f32 stats,
    activation-dtype output.

    Trailing-axis calls dispatch through the Pallas kernel layer when
    the MXNET_PALLAS gate selects it (ops/kernels/norm.py: one VMEM
    pass per row block, fused forward+backward; fp32 forward bit-exact
    vs this reference for 128-lane-aligned widths)."""
    if axis == -1 or axis == x.ndim - 1:
        from .kernels import dispatch as _kdispatch
        from .kernels import norm as _knorm
        why = _knorm.norm_supported(x, int(x.shape[-1]))
        path, _ = _kdispatch("layernorm", supported=why is None,
                             reason=why)
        if path != "xla":
            return _knorm.layer_norm(x, gamma, beta, eps,
                                     interpret=(path == "interpret"))
    dt = _stat_dtype(x)
    xf = x.astype(dt)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return (out * gamma.astype(dt).reshape(shape)
            + beta.astype(dt).reshape(shape)).astype(x.dtype)


def group_norm(x, gamma, beta, num_groups: int, eps: float = 1e-5):
    """Reference GroupNorm (src/operator/nn/group_norm.cc). x: (N, C, ...).
    f32 stats, activation-dtype output."""
    dt = _stat_dtype(x)
    n, c = x.shape[:2]
    sp = x.shape[2:]
    xg = x.astype(dt).reshape((n, num_groups, c // num_groups) + sp)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    out = xg.reshape(x.shape)
    shape = (1, c) + (1,) * len(sp)
    return (out * gamma.astype(dt).reshape(shape)
            + beta.astype(dt).reshape(shape)).astype(x.dtype)


def instance_norm(x, gamma, beta, eps: float = 1e-5):
    """Reference InstanceNorm: normalize per (N, C) over spatial dims.
    f32 stats, activation-dtype output."""
    dt = _stat_dtype(x)
    xf = x.astype(dt)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return (out * gamma.astype(dt).reshape(shape)
            + beta.astype(dt).reshape(shape)).astype(x.dtype)


def l2_norm(x, axis=None, eps: float = 1e-10, mode: str = "instance"):
    """Reference L2Normalization."""
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
    else:
        axes = axis
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


def lrn(x, nsize: int, alpha: float = 1e-4, beta: float = 0.75,
        knorm: float = 2.0):
    """Local response normalization across channels (reference lrn.cc)."""
    sq = jnp.square(x)
    half = nsize // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[1] = (half, half)
    sqp = jnp.pad(sq, pad_cfg)
    window = [1] * x.ndim
    window[1] = nsize
    ssum = lax.reduce_window(sqp, 0.0, lax.add, tuple(window),
                             (1,) * x.ndim, "VALID")
    return x / jnp.power(knorm + alpha * ssum / nsize, beta)
