"""Operator library: registry + op definitions (TPU/XLA backed).

Reference analog: src/operator/ (~200k LoC of CPU/CUDA kernels registered into
the nnvm op registry via NNVM_REGISTER_OP). Here every op is a pure JAX
function — XLA emits the TPU kernel, Pallas covers the hand-written hot ops —
registered into a Python registry that drives the imperative invoke path, the
autograd tape, and symbolic/deferred-compute tracing.
"""
from . import registry
from . import attention
from . import kernels
from .registry import Op, register, get_op, invoke, invoke_raw, list_ops
