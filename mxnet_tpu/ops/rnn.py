"""Fused multi-layer recurrent ops via ``lax.scan``.

Reference analog: the monolithic ``RNN`` operator (src/operator/rnn-inl.h:421
cuDNN descriptors, src/operator/rnn_impl.h native CPU LSTM/GRU/vanilla
kernels). TPU-native design: the input projection for ALL timesteps is one
large MXU matmul (``x @ W_ih^T`` over the flattened T*N batch), and only the
inherently sequential hidden-to-hidden recurrence runs under ``lax.scan`` —
XLA compiles the scan body once and keeps the carried state in registers/VMEM.
Gate order parity: LSTM [i, f, g, o], GRU [r, z, n] (cuDNN order, matching
rnn_impl.h so converted checkpoints drop in).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["GATES", "fused_rnn", "scan_reference",
           "rnn_packed_param_size"]

GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _step_fns(mode: str):
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def step(carry, xw_t, w_hh, b_hh):
            h = carry[0]
            h_new = act(xw_t + h @ w_hh.T + b_hh)
            return (h_new,), h_new
        return step
    if mode == "lstm":
        def step(carry, xw_t, w_hh, b_hh):
            h, c = carry
            gates = xw_t + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step
    if mode == "gru":
        def step(carry, xw_t, w_hh, b_hh):
            h = carry[0]
            # reset gate applies to the h2h *new-gate* projection only
            hw = h @ w_hh.T + b_hh
            xr, xz, xn = jnp.split(xw_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hw, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1.0 - z) * n + z * h
            return (h_new,), h_new
        return step
    raise MXNetError(f"unknown RNN mode {mode!r}")


def scan_reference(xw, h0, c0, w_hh, b_hh, mode, reverse=False):
    """The ``lax.scan`` XLA reference recurrence over precomputed input
    projections ``xw`` (T, N, G*H) — the numeric oracle the Pallas
    time-fused kernel (ops/kernels/rnn_scan.py) is bit-parity-tested
    against, and the automatic fallback tier of its dispatch."""
    step = _step_fns(mode)
    carry0 = (h0, c0) if mode == "lstm" else (h0,)

    def body(carry, xw_t):
        return step(carry, xw_t, w_hh, b_hh)

    carry, ys = lax.scan(body, carry0, xw, reverse=reverse)
    if reverse:
        pass  # lax.scan(reverse=True) already emits ys in forward time order
    h_t = carry[0]
    c_t = carry[1] if mode == "lstm" else None
    return ys, h_t, c_t


def _one_direction(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, reverse):
    """x: (T, N, C) → (ys (T, N, H), h_T, c_T|None). One MXU matmul for all
    input projections, then the recurrence: the Pallas time-fused scan
    kernel where the MXNET_PALLAS gate selects it, else the lax.scan
    reference (identical math; ops/kernels/rnn_scan.py)."""
    xw = x @ w_ih.T + b_ih                      # (T, N, G*H)
    from .kernels.rnn_scan import rnn_scan
    return rnn_scan(xw, h0, c0, w_hh, b_hh, mode, reverse=reverse)


def fused_rnn(x, h0, c0, params: Sequence, mode: str, num_layers: int,
              bidirectional: bool, dropout: float = 0.0,
              train: bool = False, key=None):
    """Multi-layer (optionally bidirectional) recurrence.

    x: (T, N, C); h0/c0: (L*D, N, H); params: flat per-(layer, direction)
    [w_ih, w_hh, b_ih, b_hh] * L * D. Returns (y, h_out, c_out|None).
    Inter-layer dropout matches the reference RNN op's p parameter
    (applied to each layer's output except the last, training only).
    """
    if mode not in GATES:
        raise MXNetError(f"unknown RNN mode {mode!r}")
    dirs = 2 if bidirectional else 1
    if len(params) != 4 * num_layers * dirs:
        raise MXNetError(
            f"expected {4 * num_layers * dirs} param arrays, got {len(params)}")
    hs, cs = [], []
    inp = x
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = (layer * dirs + d) * 4
            w_ih, w_hh, b_ih, b_hh = params[idx:idx + 4]
            s = layer * dirs + d
            c0_s = c0[s] if c0 is not None else None
            y, h_t, c_t = _one_direction(
                inp, h0[s], c0_s, w_ih, w_hh, b_ih, b_hh, mode,
                reverse=(d == 1))
            outs.append(y)
            hs.append(h_t)
            if c_t is not None:
                cs.append(c_t)
        inp = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if train and dropout > 0.0 and layer < num_layers - 1:
            if key is None:
                raise MXNetError("dropout in fused_rnn requires an rng key")
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - dropout, inp.shape)
            inp = jnp.where(keep, inp / (1.0 - dropout), 0.0)
    h_out = jnp.stack(hs, axis=0)
    c_out = jnp.stack(cs, axis=0) if cs else None
    return inp, h_out, c_out


def rnn_packed_param_size(mode: str, input_size: int, hidden_size: int,
                          num_layers: int, bidirectional: bool) -> int:
    """Total scalar count of the reference RNN op's packed parameter vector
    (rnn-inl.h GetParamSize) — used by checkpoint conversion utilities."""
    g = GATES[mode]
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden_size * dirs
        per_dir = g * hidden_size * (in_sz + hidden_size + 2)
        total += per_dir * dirs
    return total
